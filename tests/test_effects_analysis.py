"""The interprocedural effect engine (analysis/effects.py) and the four
rule families built on it (ASY001, DET001, MUT001, LCK001).

Engine unit tests pin the call-graph semantics the rules depend on —
seed tables, propagation, laundering seams, honest widening (ambiguous
and dynamic calls recorded as unresolved, never guessed), the
``effect-ok`` origin-sanction pragma — then per-rule positive/negative
fixture pairs, the ``--effects``/``--expect-json-version`` CLI surface,
the partial-run contract for the new rule ids, and runtime regression
tests for the genuine findings this PR's rules surfaced and fixed
(CrdtMap/LWWMap ``_mut`` epochs, fold-writeback bumps, Core.open
warming the native build off-loop).

Fixtures are parsed, never executed.
"""

from __future__ import annotations

import asyncio
import json
import pathlib
import textwrap
import uuid

import numpy as np
import pytest

from crdt_enc_tpu.analysis import Project, run, unsuppressed_errors
from crdt_enc_tpu.analysis.cli import main as cli_main
from crdt_enc_tpu.analysis.effects import (
    KIND_BLOCKS,
    KIND_RNG,
    KIND_WALL,
    effect_index,
)

REPO = pathlib.Path(__file__).resolve().parent.parent


def project_of(tmp_path, files: dict) -> Project:
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return Project(tmp_path)


def errors_of(tmp_path, files, rules):
    findings = run(project_of(tmp_path, files), rules, None)
    return unsuppressed_errors(findings)


def one_func(idx, qualname):
    (fi,) = idx.lookup(qualname)
    return fi


# ----------------------------------------------------- effect engine


def test_direct_seeds_classified(tmp_path):
    idx = effect_index(project_of(tmp_path, {
        "crdt_enc_tpu/m.py": """\
            import os
            import time

            def sleeper():
                time.sleep(1)

            def clocky():
                return time.time()

            def dicey():
                return os.urandom(8)
            """,
    }))
    assert one_func(idx, "sleeper").effect_kinds() == {KIND_BLOCKS}
    assert one_func(idx, "clocky").effect_kinds() == {KIND_WALL}
    assert one_func(idx, "dicey").effect_kinds() == {KIND_RNG}


def test_propagation_builds_provenance_chain(tmp_path):
    idx = effect_index(project_of(tmp_path, {
        "crdt_enc_tpu/m.py": """\
            import time

            def leaf():
                time.sleep(1)

            def mid():
                leaf()

            def top():
                mid()
            """,
    }))
    top = one_func(idx, "top")
    assert KIND_BLOCKS in top.effect_kinds()
    chain = idx.chain(top.key, KIND_BLOCKS, "time.sleep")
    # caller-first: top -> mid, mid -> leaf, leaf: the sleep itself
    assert len(chain) == 3
    assert "top" in chain[0] and "mid" in chain[1] and "time.sleep" in chain[2]


def test_awaits_effect_does_not_propagate(tmp_path):
    idx = effect_index(project_of(tmp_path, {
        "crdt_enc_tpu/m.py": """\
            import asyncio

            async def inner():
                await asyncio.sleep(0)

            def outer():
                return inner()
            """,
    }))
    assert "awaits" in one_func(idx, "inner").effect_kinds()
    assert "awaits" not in one_func(idx, "outer").effect_kinds()


def test_to_thread_and_executor_launder_blocks(tmp_path):
    idx = effect_index(project_of(tmp_path, {
        "crdt_enc_tpu/m.py": """\
            import asyncio
            import functools
            import time

            def work():
                time.sleep(1)

            async def laundered():
                await asyncio.to_thread(work)

            async def laundered_partial(loop):
                await loop.run_in_executor(None, functools.partial(work))

            async def guilty():
                work()
            """,
    }))
    assert KIND_BLOCKS not in one_func(idx, "laundered").effect_kinds()
    assert KIND_BLOCKS not in one_func(idx, "laundered_partial").effect_kinds()
    assert KIND_BLOCKS in one_func(idx, "guilty").effect_kinds()


def test_ambiguous_and_dynamic_calls_widen_honestly(tmp_path):
    """2+ same-named defs and non-name callees are recorded as
    unresolved — never resolved by guess, never silently dropped."""
    idx = effect_index(project_of(tmp_path, {
        "crdt_enc_tpu/a.py": """\
            import time

            def helper():
                time.sleep(1)
            """,
        "crdt_enc_tpu/b.py": """\
            def helper():
                return 2
            """,
        "crdt_enc_tpu/c.py": """\
            def caller(obj):
                obj.helper()

            def dyn(fns):
                fns[0]()
            """,
    }))
    caller = one_func(idx, "caller")
    # the ambiguity must NOT leak a.helper's blocks effect into caller
    assert KIND_BLOCKS not in caller.effect_kinds()
    assert any("ambiguous" in u.desc for u in caller.unresolved)
    dyn = one_func(idx, "dyn")
    assert any("dynamic call" in u.desc for u in dyn.unresolved)
    assert not dyn.effect_kinds()


def test_effect_ok_pragma_sanctions_that_line_only(tmp_path):
    idx = effect_index(project_of(tmp_path, {
        "crdt_enc_tpu/m.py": """\
            def build():
                with open("x", "w") as f:  # lint: effect-ok=blocks (one-shot)
                    f.write("y")

            def plain():
                with open("x") as f:
                    return f.read()
            """,
    }))
    build = one_func(idx, "build")
    assert KIND_BLOCKS not in build.effect_kinds()
    assert [(k, d) for k, _ln, d in build.sanctioned] == [
        (KIND_BLOCKS, "call to open")
    ]
    # a pragma sanctions its own line, not the origin everywhere
    assert KIND_BLOCKS in one_func(idx, "plain").effect_kinds()


# ------------------------------------------------------------- ASY001


def test_asy_blocking_in_async_caught_with_chain(tmp_path):
    errors = errors_of(tmp_path, {
        "crdt_enc_tpu/serve/m.py": """\
            import time

            def decode():
                time.sleep(1)

            async def cycle():
                decode()
            """,
    }, ["ASY001"])
    (f,) = errors
    assert "time.sleep" in f.message and f.context == "cycle"
    assert f.chain and "decode" in f.chain[0]


def test_asy_to_thread_seam_passes(tmp_path):
    assert not errors_of(tmp_path, {
        "crdt_enc_tpu/serve/m.py": """\
            import asyncio
            import time

            def decode():
                time.sleep(1)

            async def cycle():
                await asyncio.to_thread(decode)
            """,
    }, ["ASY001"])


def test_asy_out_of_scope_async_passes(tmp_path):
    assert not errors_of(tmp_path, {
        "crdt_enc_tpu/utils/m.py": """\
            import time

            async def helper():
                time.sleep(1)
            """,
    }, ["ASY001"])


def test_asy_sync_section_await_caught(tmp_path):
    src = """\
        async def seal(self):
            # lint: sync-section-begin
            d = self._data
            await self.storage.put(d)
            # lint: sync-section-end
            return d
        """
    (f,) = errors_of(
        tmp_path, {"crdt_enc_tpu/core/m.py": src}, ["ASY001"]
    )
    assert "sync section" in f.message and f.line == 4


def test_asy_sync_section_clean_and_unterminated(tmp_path):
    assert not errors_of(tmp_path, {
        "crdt_enc_tpu/core/ok.py": """\
            async def seal(self):
                # lint: sync-section-begin
                d = self._data
                cut = sorted(d)
                # lint: sync-section-end
                await self.storage.put(cut)
            """,
    }, ["ASY001"])
    (f,) = errors_of(tmp_path, {
        "crdt_enc_tpu/core/bad.py": """\
            async def seal(self):
                # lint: sync-section-begin
                d = self._data
                return d
            """,
    }, ["ASY001"])
    assert "without a matching" in f.message


# ------------------------------------------------------------- DET001


def test_det_wall_clock_on_sim_surface_caught(tmp_path):
    (f,) = errors_of(tmp_path, {
        "crdt_enc_tpu/sim/m.py": """\
            import time

            def stamp():
                return time.time()
            """,
    }, ["DET001"])
    assert "wall_clock" in f.message and "time.time" in f.message


def test_det_daemon_module_is_a_surface(tmp_path):
    (f,) = errors_of(tmp_path, {
        "crdt_enc_tpu/serve/daemon.py": """\
            import random

            def roll():
                return random.random()
            """,
    }, ["DET001"])
    assert "rng" in f.message


def test_det_seeded_seams_pass(tmp_path):
    """uuid4 rides the ContextVar dispatch seam; a clock= parameter is a
    dynamic call (honestly unresolved); seeded Random(seed) is not an
    rng effect."""
    assert not errors_of(tmp_path, {
        "crdt_enc_tpu/sim/m.py": """\
            import random
            import uuid

            def fresh_id():
                return uuid.uuid4()

            def step(clock):
                return clock()

            def rng_for(seed):
                return random.Random(seed)
            """,
    }, ["DET001"])


# ------------------------------------------------------------- MUT001


def test_mut_unbumped_and_one_branch_caught(tmp_path):
    errors = errors_of(tmp_path, {
        "crdt_enc_tpu/m.py": """\
            from dataclasses import dataclass, field

            @dataclass
            class State:
                entries: dict = field(default_factory=dict)
                clock: dict = field(default_factory=dict)
                _mut: int = field(default=0, compare=False, repr=False)

                def never(self, k, v):
                    self.entries[k] = v

                def one_branch(self, k, v):
                    if k in self.entries:
                        self._mut += 1
                    self.entries[k] = v
            """,
    }, ["MUT001"])
    by_ctx = {f.context: f for f in errors}
    assert set(by_ctx) == {"State.never", "State.one_branch"}
    assert "never bumps" in by_ctx["State.never"].message
    assert "one branch" in by_ctx["State.one_branch"].message


def test_mut_dominating_bump_and_alias_write_semantics(tmp_path):
    errors = errors_of(tmp_path, {
        "crdt_enc_tpu/m.py": """\
            from dataclasses import dataclass, field

            @dataclass
            class State:
                entries: dict = field(default_factory=dict)
                _mut: int = field(default=0, compare=False, repr=False)

                def good(self, k, v):
                    self._mut += 1
                    if v:
                        self.entries[k] = v

                def via_alias(self, k):
                    e = self.entries
                    e.pop(k, None)
            """,
    }, ["MUT001"])
    assert [f.context for f in errors] == ["State.via_alias"]
    assert "alias" in errors[0].message


def test_mut_private_helper_obligation_moves_to_callers(tmp_path):
    assert not errors_of(tmp_path, {
        "crdt_enc_tpu/m.py": """\
            from dataclasses import dataclass, field

            @dataclass
            class State:
                entries: dict = field(default_factory=dict)
                _mut: int = field(default=0, compare=False, repr=False)

                def apply(self, k, v):
                    self._mut += 1
                    self._store(k, v)

                def _store(self, k, v):
                    self.entries[k] = v
            """,
    }, ["MUT001"])


def test_mut_module_writeback_needs_bump_unless_fresh(tmp_path):
    errors = errors_of(tmp_path, {
        "crdt_enc_tpu/m.py": """\
            from dataclasses import dataclass, field

            @dataclass
            class State:
                entries: dict = field(default_factory=dict)
                clock: dict = field(default_factory=dict)
                _mut: int = field(default=0, compare=False, repr=False)

            def writeback(state, clock):
                state.clock = clock

            def writeback_bumped(state, clock):
                state._mut += 1
                state.clock = clock

            def fresh_build(clock):
                s = State()
                s.clock = clock
                return s
            """,
    }, ["MUT001"])
    (f,) = errors
    assert f.context == "writeback" and "state._mut" in f.message


# ------------------------------------------------------------- LCK001


def test_lck_unlocked_access_of_guarded_field_caught(tmp_path):
    (f,) = errors_of(tmp_path, {
        "crdt_enc_tpu/m.py": """\
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def put(self, x):
                    with self._lock:
                        self._items.append(x)

                def peek(self):
                    return self._items[-1]
            """,
    }, ["LCK001"])
    assert f.context == "Box.peek" and "_items" in f.message


def test_lck_consistent_locking_passes(tmp_path):
    assert not errors_of(tmp_path, {
        "crdt_enc_tpu/m.py": """\
            import threading

            class Box:
                def __init__(self):
                    self._lock = threading.Lock()
                    self._items = []

                def put(self, x):
                    with self._lock:
                        self._items.append(x)

                def peek(self):
                    with self._lock:
                        return self._items[-1]
            """,
    }, ["LCK001"])


def test_lck_await_under_threading_lock_caught(tmp_path):
    errors = errors_of(tmp_path, {
        "crdt_enc_tpu/m.py": """\
            import asyncio
            import threading

            _LOCK = threading.Lock()

            async def bad():
                with _LOCK:
                    await asyncio.sleep(0)

            async def fine(lock: asyncio.Lock):
                async with lock:
                    await asyncio.sleep(0)
            """,
    }, ["LCK001"])
    (f,) = errors
    assert f.context == "bad" and "parks the event loop" in f.message


# ---------------------------------------------------------------- CLI


_REGISTRY_DOC = textwrap.dedent(
    """\
    # registry fixture

    ## Span registry

    | name | where |
    |---|---|
    | `phase.x` | fixture |
    | `stream.h2d` | fixture |

    ## Counter & gauge registry

    | name | where |
    |---|---|
    | `h2d_bytes` | fixture |
    | `events_dropped` | obs-internal |
    """
)


def _mini_checkout(tmp_path, src):
    (tmp_path / "crdt_enc_tpu").mkdir()
    (tmp_path / "crdt_enc_tpu" / "mod.py").write_text(textwrap.dedent(src))
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text(_REGISTRY_DOC)


def test_cli_effects_dump_text(tmp_path, capsys):
    _mini_checkout(tmp_path, """\
        import time

        def leaf():
            time.sleep(1)

        async def top():
            leaf()
        """)
    assert cli_main(["--effects", "top", "--root", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "async def top" in out
    assert "blocks: time.sleep" in out
    assert "via" in out


def test_cli_effects_json_schema(tmp_path, capsys):
    _mini_checkout(tmp_path, """\
        import time

        def leaf(fns):
            fns[0]()
            return time.time()
        """)
    rc = cli_main(["--effects", "leaf", "--json", "--root", str(tmp_path)])
    assert rc == 0
    out = json.loads(capsys.readouterr().out)
    assert out["version"] == 2
    (fn,) = out["functions"]
    assert set(fn) == {
        "key", "qualname", "async", "effects", "unresolved", "sanctioned",
    }
    (eff,) = fn["effects"]
    assert eff["kind"] == "wall_clock" and eff["chain"]
    assert fn["unresolved"][0]["desc"].startswith("dynamic call")


def test_cli_effects_unknown_qualname_is_usage_error(tmp_path, capsys):
    _mini_checkout(tmp_path, "def f():\n    pass\n")
    assert cli_main(["--effects", "nope.missing", "--root", str(tmp_path)]) == 2
    assert "no function matching" in capsys.readouterr().err


def test_cli_expect_json_version_pins_consumers(tmp_path, capsys):
    _mini_checkout(tmp_path, "def f():\n    pass\n")
    args = ["--json", "--rule", "THR001", "--root", str(tmp_path)]
    assert cli_main(["--expect-json-version", "1", *args]) == 2
    assert "schema version mismatch" in capsys.readouterr().err
    assert cli_main(["--expect-json-version", "2", *args]) == 0


def test_cli_partial_run_new_rules_no_spurious_findings(capsys):
    """The path-subset contract extends to the new families: a
    single-file run on a live module exits clean — no stale-baseline
    errors, no findings that depend on modules outside the subset."""
    rc = cli_main([
        "--rule", "ASY001", "--rule", "DET001", "--rule", "MUT001",
        "--rule", "LCK001", "--diff-baseline",
        str(REPO / "crdt_enc_tpu" / "models" / "orset.py"),
        "--root", str(REPO),
    ])
    assert rc == 0
    assert "0 error(s)" in capsys.readouterr().out


# ---------------------------------- genuine-finding runtime regressions


def test_crdtmap_mut_epoch_bumps_on_apply_and_merge():
    from crdt_enc_tpu.models import CrdtMap
    from crdt_enc_tpu.models.orset import AddOp

    actor = uuid.UUID(int=1).bytes
    m = CrdtMap(child=b"orset")
    before = m._mut
    op = m.update_ctx(actor, "k", lambda child, dot: AddOp("v", dot))
    assert m._mut == before  # deriving an op must NOT mutate
    m.apply(op)
    assert m._mut > before
    other = CrdtMap(child=b"orset")
    other.apply(other.update_ctx(uuid.UUID(int=2).bytes, "k2",
                                 lambda child, dot: AddOp("w", dot)))
    mid = m._mut
    m.merge(other)
    assert m._mut > mid


def test_lwwmap_mut_epoch_bumps_on_apply_and_merge():
    from crdt_enc_tpu.models.lwwmap import LWWMap

    a, b = LWWMap(), LWWMap()
    actor = uuid.UUID(int=1).bytes
    before = a._mut
    a.apply(a.put("k", 1, actor, "v"))
    assert a._mut > before
    b.apply(b.put("k", 2, actor, "w"))
    mid = a._mut
    a.merge(b)
    assert a._mut > mid
    # the epoch is bookkeeping, not state: equal maps stay equal
    assert a == LWWMap.from_obj(a.to_obj())


def test_crdtmap_fold_writeback_bumps_epoch():
    from crdt_enc_tpu.models import CrdtMap, canonical_bytes
    from crdt_enc_tpu.models.orset import AddOp
    from crdt_enc_tpu.parallel.accel import TpuAccelerator
    from crdt_enc_tpu.utils import codec

    actor = uuid.UUID(int=1).bytes
    proto = CrdtMap(child=b"orset")
    oracle = CrdtMap(child=b"orset")
    ops = []
    for i in range(3):
        op = oracle.update_ctx(actor, f"k{i}",
                               lambda child, dot: AddOp("v", dot))
        oracle.apply(op)
        ops.append(op)
    payloads = [codec.pack([proto.op_to_obj(op) for op in ops])]
    folded = CrdtMap(child=b"orset")
    before = folded._mut
    ok = TpuAccelerator(min_device_batch=1).fold_payloads(
        folded, payloads, actors_hint=[actor]
    )
    assert ok
    assert folded._mut > before, "fold writeback must invalidate caches"
    assert canonical_bytes(folded) == canonical_bytes(oracle)


def test_orset_fresh_fold_native_self_bumps():
    from crdt_enc_tpu import native
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.ops.columnar import (
        KIND_ADD,
        Vocab,
        _orset_fresh_fold_native,
    )

    try:
        native.load_state()
    except Exception:
        pytest.skip("native state library unavailable")
    members = Vocab(["m0", "m1"])
    replicas = Vocab([uuid.UUID(int=1).bytes])
    state = ORSet()
    folded = _orset_fresh_fold_native(
        state,
        np.array([KIND_ADD, KIND_ADD], np.int8),
        np.array([0, 1], np.int64),
        np.array([0, 0], np.int64),
        np.array([1, 2], np.int64),
        members, replicas,
        np.zeros(1, np.int64),
    )
    assert folded is not None
    assert folded._mut > 0, "native writeback must self-protect the epoch"


def test_core_open_warms_native_off_loop(monkeypatch):
    from crdt_enc_tpu import native
    from crdt_enc_tpu.backends import (
        IdentityCryptor,
        MemoryRemote,
        MemoryStorage,
        PlainKeyCryptor,
    )
    from crdt_enc_tpu.core import Core, OpenOptions, gcounter_adapter
    from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

    calls = []
    monkeypatch.setattr(native, "warm", lambda: calls.append(True))

    async def go():
        await Core.open(OpenOptions(
            storage=MemoryStorage(MemoryRemote()),
            cryptor=IdentityCryptor(),
            key_cryptor=PlainKeyCryptor(),
            adapter=gcounter_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=True,
        ))

    asyncio.run(go())
    assert calls, "Core.open must warm the native build before first pack"


def test_native_warm_swallows_build_failure(monkeypatch):
    from crdt_enc_tpu import native

    def boom():
        raise RuntimeError("no compiler on this box")

    monkeypatch.setattr(native, "load", boom)
    monkeypatch.setattr(native, "load_state", boom)
    native.warm()  # must not raise: pack() falls back to Python paths

"""Sharded fold/merge on the virtual 8-device CPU mesh, and the TPU
accelerator plugged into the live core."""

import asyncio
import uuid

import jax
import numpy as np
import pytest

from crdt_enc_tpu import ops as K
from crdt_enc_tpu import parallel as par
from crdt_enc_tpu.models import ORSet, canonical_bytes
from crdt_enc_tpu.backends import IdentityCryptor, MemoryRemote, MemoryStorage, PlainKeyCryptor
from crdt_enc_tpu.core import Core, OpenOptions, orset_adapter
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

ACTORS = [uuid.UUID(int=i + 1).bytes for i in range(6)]


def build_history(n_ops=200, n_members=16):
    state = ORSet()
    ops = []
    for i in range(n_ops):
        a = ACTORS[i % len(ACTORS)]
        m = i % n_members
        if i % 7 == 6:
            op = state.rm_ctx(m)
            if op.ctx.is_empty():
                continue
        else:
            op = state.add_ctx(a, m)
        state.apply(op)
        ops.append(op)
    return state, ops


def test_sharded_fold_matches_host():
    assert len(jax.devices()) == 8, "conftest must provide 8 virtual devices"
    host, ops = build_history()
    members, replicas = K.Vocab(list(range(16))), K.Vocab(ACTORS)
    cols = K.orset_ops_to_columns(ops, members, replicas)
    clock0, add0, rm0 = K.orset_state_to_planes(ORSet(), members, replicas)
    E, R = len(members), len(replicas)

    for dp, mp in [(8, 1), (4, 2), (2, 4), (1, 8)]:
        mesh = par.make_mesh((dp, mp))
        c2 = K.orset_ops_to_columns(ops, members, replicas)
        c2 = par.pad_rows_for_mesh(c2, dp, R)
        clock, add, rm = par.orset_fold_sharded(
            mesh, clock0, add0, rm0, c2.kind, c2.member, c2.actor, c2.counter
        )
        device = K.orset_planes_to_state(
            np.asarray(clock), np.asarray(add), np.asarray(rm), members, replicas
        )
        assert canonical_bytes(device) == canonical_bytes(host), (dp, mp)


def test_sharded_fold_pallas_matches_host():
    """The pallas-sharded route (each shard's scatter on the flagship
    kernel, interpret mode here) must match the host fold on every mesh
    split — including mp slices whose member range is not 8-aligned."""
    host, ops = build_history()
    members, replicas = K.Vocab(list(range(16))), K.Vocab(ACTORS)
    clock0, add0, rm0 = K.orset_state_to_planes(ORSet(), members, replicas)
    E, R = len(members), len(replicas)

    for dp, mp in [(4, 2), (2, 4), (1, 8)]:
        mesh = par.make_mesh((dp, mp))
        c2 = K.orset_ops_to_columns(ops, members, replicas)
        c2 = par.pad_rows_for_mesh(c2, dp, R)
        cap = par.sharded_fold_cap(c2.member, E, dp, mp)
        clock, add, rm = par.orset_fold_sharded(
            mesh, clock0, add0, rm0, c2.kind, c2.member, c2.actor,
            c2.counter, impl="pallas", tile_cap=cap, interpret=True,
        )
        device = K.orset_planes_to_state(
            np.asarray(clock), np.asarray(add), np.asarray(rm), members,
            replicas,
        )
        assert canonical_bytes(device) == canonical_bytes(host), (dp, mp)


def test_sharded_merge_matches_host():
    sa, _ = build_history(100)
    sb, _ = build_history(80)
    host = ORSet.from_obj(sa.to_obj())
    host.merge(sb)
    members, replicas = K.Vocab(list(range(16))), K.Vocab(ACTORS)
    ca, aa, ra = K.orset_state_to_planes(sa, members, replicas)
    cb, ab, rb = K.orset_state_to_planes(sb, members, replicas)
    mesh = par.make_mesh((1, 8))
    clock, add, rm = par.orset_merge_sharded(mesh, ca, aa, ra, cb, ab, rb)
    device = K.orset_planes_to_state(
        np.asarray(clock), np.asarray(add), np.asarray(rm), members, replicas
    )
    assert canonical_bytes(device) == canonical_bytes(host)


def test_accelerated_core_matches_host_core():
    """Two cores fold the same remote — one with the host loop, one with the
    TPU accelerator — and must land on identical canonical bytes."""

    async def go():
        remote = MemoryRemote()

        def opts(accel=None):
            kw = {"accelerator": accel} if accel else {}
            return OpenOptions(
                storage=MemoryStorage(remote),
                cryptor=IdentityCryptor(),
                key_cryptor=PlainKeyCryptor(),
                adapter=orset_adapter(),
                supported_data_versions=(DEFAULT_DATA_VERSION_1,),
                current_data_version=DEFAULT_DATA_VERSION_1,
                create=True,
                **kw,
            )

        producer = await Core.open(opts())
        for m in range(30):
            await producer.update(lambda s, m=m: s.add_ctx(producer.actor_id, m % 23))
        for m in (1, 5, 9):
            await producer.update(lambda s, m=m: s.rm_ctx(m))
        for m in range(12):
            await producer.update(
                lambda s, m=m: s.add_ctx(producer.actor_id, (m * 5) % 23)
            )

        host_core = await Core.open(opts())
        accel_core = await Core.open(
            opts(accel=par.TpuAccelerator(min_device_batch=1))
        )
        await host_core.read_remote()
        await accel_core.read_remote()
        assert host_core.with_state(canonical_bytes) == accel_core.with_state(
            canonical_bytes
        )
        # and compaction through the accelerator round-trips
        await accel_core.compact()
        fresh = await Core.open(opts())
        await fresh.read_remote()
        assert fresh.with_state(canonical_bytes) == host_core.with_state(
            canonical_bytes
        )

    asyncio.run(go())


def test_sharded_pncounter_matches_whole():
    import numpy as np

    R, N = 24, 256
    rng = np.random.default_rng(7)
    actor = rng.integers(0, R + 1, N).astype(np.int32)  # incl. sentinels
    sign = (rng.random(N) < 0.4).astype(np.int8)
    counter = rng.integers(1, 30, N).astype(np.int32)
    p0 = rng.integers(0, 5, R).astype(np.int32)
    n0 = rng.integers(0, 5, R).astype(np.int32)

    mesh = par.make_mesh((8, 1))
    ps, ns, vs = par.pncounter_fold_sharded(mesh, p0, n0, sign, actor, counter)
    pw, nw, vw = K.pncounter_fold(p0, n0, sign, actor, counter, num_replicas=R)
    assert np.array_equal(np.asarray(ps), np.asarray(pw))
    assert np.array_equal(np.asarray(ns), np.asarray(nw))
    assert int(vs) == int(vw)


def test_sharded_gcounter_matches_whole():
    import numpy as np

    R, N = 10, 128
    rng = np.random.default_rng(8)
    actor = rng.integers(0, R, N).astype(np.int32)
    counter = rng.integers(1, 20, N).astype(np.int32)
    clock0 = np.zeros(R, np.int32)
    mesh = par.make_mesh((8, 1))
    cs, ts = par.gcounter_fold_sharded(mesh, clock0, actor, counter)
    cw, tw = K.gcounter_fold(clock0, actor, counter, num_replicas=R)
    assert np.array_equal(np.asarray(cs), np.asarray(cw))
    assert int(ts) == int(tw)


def test_sharded_lww_matches_whole():
    import numpy as np

    Kk, N = 40, 512
    rng = np.random.default_rng(9)
    key = rng.integers(0, Kk + 1, N).astype(np.int32)  # incl. sentinels
    hi = rng.integers(0, 4, N).astype(np.int32)
    lo = rng.integers(0, 100, N).astype(np.int32)
    actor = rng.integers(0, 16, N).astype(np.int32)
    value = rng.integers(0, 50, N).astype(np.int32)

    mesh = par.make_mesh((8, 1))
    sharded = par.lww_fold_sharded(mesh, key, hi, lo, actor, value, num_keys=Kk)
    whole = K.lww_fold(key, hi, lo, actor, value, num_keys=Kk)
    for a, b in zip(sharded, whole):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def _add_many(core, members):
    """One update carrying an add per member, dots advancing correctly:
    each op applies before the next derives (the re-fold in apply_ops is
    idempotent), so the whole batch folds in one accelerator call."""

    def build(s):
        ops = []
        for m in members:
            op = s.add_ctx(core.actor_id, m)
            s.apply(op)
            ops.append(op)
        return ops

    return build


def _mesh_opts_factory(remote):
    def opts(accel=None, adapter=None):
        kw = {"accelerator": accel} if accel else {}
        return OpenOptions(
            storage=MemoryStorage(remote),
            cryptor=IdentityCryptor(),
            key_cryptor=PlainKeyCryptor(),
            adapter=adapter or orset_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=True,
            **kw,
        )

    return opts


def test_mesh_core_compaction_matches_host():
    """Multi-chip as a capability, not a library: a Core whose accelerator
    carries a >1-device mesh routes every fold/merge through the shard_map
    SPMD kernels, and the whole lifecycle (open → apply_ops → read_remote →
    compact → re-join) lands byte-identical to the single-device host run."""

    async def go(dp, mp):
        mesh = par.make_mesh((dp, mp))
        remote = MemoryRemote()
        opts = _mesh_opts_factory(remote)
        maccel = par.TpuAccelerator(min_device_batch=1, mesh=mesh)

        producer = await Core.open(opts(maccel))
        await producer.update(
            _add_many(producer, [m % 17 for m in range(40)])
        )
        await producer.update(
            lambda s: [s.rm_ctx(m) for m in (2, 7, 11)]
        )
        await producer.compact()  # sharded fold feeds the snapshot

        # a second writer adds a tail beyond the snapshot
        writer2 = await Core.open(opts(maccel))
        await writer2.update(
            _add_many(writer2, [100 + m for m in range(9)])
        )

        host = await Core.open(opts())
        mesh_core = await Core.open(opts(maccel))
        await host.read_remote()
        await mesh_core.read_remote()  # sharded state merge + op fold
        assert mesh_core.with_state(canonical_bytes) == host.with_state(
            canonical_bytes
        ), (dp, mp)

        # second compaction: snapshot + tail merge, all SPMD, round-trips
        await mesh_core.compact()
        fresh = await Core.open(opts())
        await fresh.read_remote()
        assert fresh.with_state(canonical_bytes) == host.with_state(
            canonical_bytes
        ), (dp, mp)

    for dp, mp in [(4, 2), (8, 1)]:
        asyncio.run(go(dp, mp))


def test_mesh_accel_counters_and_lww_match_host():
    """The mesh-routed accelerator's counter and LWW folds must equal the
    host loops through the live core."""
    from crdt_enc_tpu.core import lwwmap_adapter, pncounter_adapter

    async def go():
        mesh = par.make_mesh((4, 2))
        maccel = par.TpuAccelerator(min_device_batch=1, mesh=mesh)

        # PN-counter
        remote = MemoryRemote()
        opts = _mesh_opts_factory(remote)
        prod = await Core.open(opts(adapter=pncounter_adapter()))

        def pn_ops(s):
            ops = []
            for i in range(25):
                op = (
                    s.inc(prod.actor_id, i + 1)
                    if i % 3
                    else s.dec(prod.actor_id, i + 1)
                )
                s.apply(op)
                ops.append(op)
            return ops

        await prod.update(pn_ops)
        host = await Core.open(opts(adapter=pncounter_adapter()))
        meshc = await Core.open(opts(maccel, adapter=pncounter_adapter()))
        await host.read_remote()
        await meshc.read_remote()
        assert meshc.with_state(canonical_bytes) == host.with_state(
            canonical_bytes
        )
        assert meshc.with_state(lambda s: s.read()) == host.with_state(
            lambda s: s.read()
        )

        # LWW map
        remote = MemoryRemote()
        opts = _mesh_opts_factory(remote)
        prod = await Core.open(opts(adapter=lwwmap_adapter()))
        # LWW ops carry explicit timestamps — no dot bookkeeping, so one
        # batch update is safe without applying between derivations
        await prod.update(
            lambda s: [
                s.put(i % 11, 1000 + i, prod.actor_id, i * 3)
                for i in range(40)
            ]
        )
        await prod.update(lambda s: s.delete(4, 5000, prod.actor_id))
        host = await Core.open(opts(adapter=lwwmap_adapter()))
        meshc = await Core.open(opts(maccel, adapter=lwwmap_adapter()))
        await host.read_remote()
        await meshc.read_remote()
        assert meshc.with_state(canonical_bytes) == host.with_state(
            canonical_bytes
        )

    asyncio.run(go())

"""Replication & convergence observability (ISSUE 6).

The watermark math is exactly asserted — not shape-checked — on both
the pure function (synthetic clocks) and a real 3-device remote where
devices seal/read at skewed rates, including the all-converged fixed
point and the one-silent-actor collapse.  The fleet aggregator and the
bench trend gate are asserted against hand-computed distributions and a
committed golden rendering (the same golden tools/run_checks.sh diffs).
"""

import asyncio
import json
import pathlib

import pytest

from crdt_enc_tpu.backends import (
    FsStorage,
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PlainKeyCryptor,
)
from crdt_enc_tpu.core import Core, OpenOptions, gcounter_adapter
from crdt_enc_tpu.obs import fleet, replication, sink
from crdt_enc_tpu.utils import trace
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1
from crdt_enc_tpu.models.vclock import VClock

DATA = pathlib.Path(__file__).parent / "data"

A = b"\xaa" * 16
B = b"\xbb" * 16
C = b"\xcc" * 16
RID = b"\x99" * 32


def run(coro):
    return asyncio.run(coro)


def make_opts(storage, **kw):
    return OpenOptions(
        storage=storage,
        cryptor=IdentityCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=gcounter_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=True,
        **kw,
    )


# ---- compute_status: the pure math ----------------------------------------


def test_compute_status_all_converged_fixed_point():
    """Every replica published a cursor equal to the union: the
    watermark IS the union, every divergence gauge is zero."""
    local = VClock({A: 3, B: 2})
    status = replication.compute_status(
        A, local, {B: VClock({A: 3, B: 2})}, [], RID,
        {A: 3, B: 2}, True,
    )
    assert status == {
        "actor": A.hex(),
        "remote_id": RID.hex(),
        "local_clock": {A.hex(): 3, B.hex(): 2},
        "union_clock": {A.hex(): 3, B.hex(): 2},
        "watermark": {A.hex(): 3, B.hex(): 2},
        "matrix": {B.hex(): {A.hex(): 3, B.hex(): 2}},
        "backlog": {"files": 0, "bytes": 0, "per_actor": {}},
        "divergence": {
            "actors_behind": 0,
            "version_lag": 0,
            "watermark_lag": 0,
            "known_replicas": 2,
        },
        "checkpoint": {
            "enabled": True,
            "sealed": True,
            "staleness_versions": 0,
        },
    }


def test_compute_status_one_silent_actor_collapses_watermark():
    """B produced ops but never published a cursor: silence is
    indistinguishable from lag, so B's row (0) kills every other
    actor's watermark entry — but B's OWN ops keep a watermark up to
    what this replica has seen (implied self-knowledge caps B's row at
    the union, the local row at 2)."""
    local = VClock({A: 3, B: 2})
    status = replication.compute_status(
        A, local, {}, [(B, 3, 100), (B, 4, 50)], RID, None, False,
    )
    assert status == {
        "actor": A.hex(),
        "remote_id": RID.hex(),
        "local_clock": {A.hex(): 3, B.hex(): 2},
        "union_clock": {A.hex(): 3, B.hex(): 4},
        "watermark": {B.hex(): 2},
        "matrix": {},
        "backlog": {
            "files": 2,
            "bytes": 150,
            "per_actor": {B.hex(): {"files": 2, "bytes": 150}},
        },
        "divergence": {
            "actors_behind": 1,
            "version_lag": 2,
            "watermark_lag": 5,  # A: 3-0, B: 4-2
            "known_replicas": 2,
        },
        "checkpoint": {
            "enabled": False,
            "sealed": False,
            "staleness_versions": 5,
        },
    }


def test_compute_status_byte_stable():
    """Same inputs → byte-identical JSON (sorted keys everywhere), so
    differential tests and fleet goldens can compare strings."""
    args = (
        C, VClock({B: 1, A: 2}), {A: VClock({A: 2})},
        [(B, 2, 7)], RID, {A: 2}, True,
    )
    one = json.dumps(replication.compute_status(*args), sort_keys=True)
    two = json.dumps(replication.compute_status(*args), sort_keys=True)
    assert one == two
    # insertion-order independence: a permuted-clock twin renders the same
    permuted = (
        C, VClock({A: 2, B: 1}), {A: VClock({A: 2})},
        [(B, 2, 7)], RID, {A: 2}, True,
    )
    assert json.dumps(
        replication.compute_status(*permuted), sort_keys=True
    ) == one


def test_compute_status_checkpoint_staleness_counts_new_versions():
    status = replication.compute_status(
        A, VClock({A: 5, B: 3}), {}, [], RID, {A: 2, B: 3}, True,
    )
    assert status["checkpoint"] == {
        "enabled": True, "sealed": True, "staleness_versions": 3,
    }


# ---- the 3-device differential fixture ------------------------------------


async def _three_devices(remote):
    """A seals early, B writes without publishing, C only reads — the
    skewed-rate choreography every stage below asserts against."""
    a = await Core.open(make_opts(MemoryStorage(remote)))
    for _ in range(3):
        await a.apply_ops([a.with_state(lambda s: s.inc(a.actor_id))])
    await a.compact()  # publishes cursor {A:3}, GCs A's op files

    b = await Core.open(make_opts(MemoryStorage(remote)))
    await b.read_remote()  # learns A's published cursor
    for _ in range(2):
        await b.apply_ops([b.with_state(lambda s: s.inc(b.actor_id))])

    c = await Core.open(make_opts(MemoryStorage(remote)))
    await c.read_remote()  # snapshot + B's op tail
    return a, b, c


def test_three_device_watermark_backlog_divergence_exact():
    async def go():
        remote = MemoryRemote()
        a, b, c = await _three_devices(remote)
        ah, bh, ch = a.actor_id.hex(), b.actor_id.hex(), c.actor_id.hex()

        # ---- stage 1: C folded everything, but B never published ----
        st = await c.replication_status()
        assert st["actor"] == ch
        assert st["local_clock"] == {ah: 3, bh: 2}
        assert st["union_clock"] == {ah: 3, bh: 2}
        assert st["matrix"] == {ah: {ah: 3}}
        # B is silent → every watermark entry collapses: A's because B
        # may know nothing of A, B's because nobody else saw past B:2
        # and B:2 needs C's OWN row too — C has it, A's published
        # cursor does not
        assert st["watermark"] == {}
        assert st["backlog"] == {"files": 0, "bytes": 0, "per_actor": {}}
        assert st["divergence"] == {
            "actors_behind": 0,
            "version_lag": 0,
            "watermark_lag": 5,
            "known_replicas": 3,
        }
        # C never sealed a checkpoint: staleness is the whole fold
        assert st["checkpoint"] == {
            "enabled": True, "sealed": False, "staleness_versions": 5,
        }
        # byte-stable across repeated probes of the same state
        again = await c.replication_status()
        assert json.dumps(st, sort_keys=True) == json.dumps(
            again, sort_keys=True
        )
        assert c.last_replication_status == again

        # ---- stage 2: B seals two MORE ops C hasn't read ----
        for _ in range(2):
            await b.apply_ops([b.with_state(lambda s: s.inc(b.actor_id))])
        st = await c.replication_status()
        nbytes = len(remote.ops[b.actor_id][3]) + len(
            remote.ops[b.actor_id][4]
        )
        assert st["backlog"] == {
            "files": 2,
            "bytes": nbytes,
            "per_actor": {bh: {"files": 2, "bytes": nbytes}},
        }
        assert st["union_clock"] == {ah: 3, bh: 4}
        assert st["divergence"] == {
            "actors_behind": 1,
            "version_lag": 2,
            "watermark_lag": 7,
            "known_replicas": 3,
        }

        # ---- stage 3: B compacts (publishes {A:3,B:4}), C reads ----
        await b.compact()
        # B's own post-compaction view: backlog zero by construction,
        # checkpoint freshly sealed, A's entry watermarked (A published
        # A:3 and B folded it), B's own entry still pinned by A's stale
        # published cursor
        stb = await b.replication_status()
        assert stb["watermark"] == {ah: 3}
        assert stb["backlog"] == {"files": 0, "bytes": 0, "per_actor": {}}
        assert stb["checkpoint"] == {
            "enabled": True, "sealed": True, "staleness_versions": 0,
        }
        assert stb["divergence"]["watermark_lag"] == 4  # B: 4-0
        # between B's compact and C's read, C is BLIND to B:3-4: the op
        # files were GC'd into a snapshot C hasn't read yet, and an
        # unread snapshot name carries no clock — divergence measures
        # what is KNOWN to exist, so it legitimately drops to 0 here
        # (the fleet view, which has B's sink record, still shows it)
        st_c = await c.replication_status()
        assert st_c["union_clock"] == {ah: 3, bh: 2}
        assert st_c["divergence"]["version_lag"] == 0
        assert st_c["backlog"] == {"files": 0, "bytes": 0, "per_actor": {}}
        await c.read_remote()
        st = await c.replication_status()
        assert st["local_clock"] == {ah: 3, bh: 4}
        assert st["matrix"] == {ah: {ah: 3}, bh: {ah: 3, bh: 4}}
        # A's published cursor predates B's ops → B's entry still open
        assert st["watermark"] == {ah: 3}
        assert st["divergence"] == {
            "actors_behind": 0,
            "version_lag": 0,
            "watermark_lag": 4,
            "known_replicas": 3,
        }

        # ---- stage 4: A re-reads + republishes → fixed point ----
        await a.read_remote()
        await a.compact()
        await c.read_remote()
        st = await c.replication_status()
        assert st["matrix"] == {
            ah: {ah: 3, bh: 4}, bh: {ah: 3, bh: 4},
        }
        assert st["watermark"] == st["union_clock"] == st["local_clock"]
        assert st["divergence"] == {
            "actors_behind": 0,
            "version_lag": 0,
            "watermark_lag": 0,
            "known_replicas": 3,
        }
        assert st["backlog"] == {"files": 0, "bytes": 0, "per_actor": {}}
        # remote identity agrees across all three devices
        assert st["remote_id"] == stb["remote_id"]
        assert st["remote_id"] == (await a.replication_status())["remote_id"]

    run(go())


def test_fs_stat_ops_matches_load_ops_sizes(tmp_path):
    """The fs backlog probe (native scan_op_sizes / stat fallback)
    sizes exactly the files load_ops would read, without reading."""
    async def go():
        remote_dir = str(tmp_path / "remote")
        s = FsStorage(str(tmp_path / "local"), remote_dir)
        core = await Core.open(make_opts(s))
        for _ in range(4):
            await core.apply_ops(
                [core.with_state(lambda st: st.inc(core.actor_id))]
            )
        wanted = [(core.actor_id, 2)]  # tail past a nonzero cursor
        stats = await s.stat_ops(wanted)
        loaded = await s.load_ops(wanted)
        assert stats == [(a, v, len(raw)) for a, v, raw in loaded]
        assert len(stats) == 3 and all(n > 0 for _, _, n in stats)
        # fully-consumed tail: empty, and cheap by construction
        assert await s.stat_ops([(core.actor_id, 5)]) == []

    run(go())


# ---- gauge sampling + sink wiring -----------------------------------------


def test_replication_gauges_sampled_on_lifecycle():
    trace.reset()

    async def go():
        remote = MemoryRemote()
        w = await Core.open(make_opts(MemoryStorage(remote)))
        await w.apply_ops([w.with_state(lambda s: s.inc(w.actor_id))])
        await w.compact()
        r = await Core.open(make_opts(MemoryStorage(remote)))
        # a fresh consumer BEFORE read_remote: open sampled its backlog
        return r

    run(go())
    snap = trace.snapshot()
    g = snap["gauges"]
    for name in (
        "repl_backlog_files", "repl_backlog_bytes", "repl_actors_behind",
        "repl_version_lag", "repl_watermark_lag", "repl_known_replicas",
        "checkpoint_staleness_versions",
    ):
        assert name in g, name
    assert snap["counters"]["repl_samples"] >= 3  # 2 opens + compact
    assert snap["spans"]["repl.status"]["count"] >= 3
    trace.reset()


def test_read_remote_sample_skips_storage_probe():
    """The read_remote sample reuses the ingest's own work: the poll
    just folded everything its listing found, so it must not pay a
    second per-actor stat_ops probe (the polling hot path) — and the
    sampled backlog gauges are zero by construction."""
    trace.reset()

    async def go():
        remote = MemoryRemote()
        w = await Core.open(make_opts(MemoryStorage(remote)))
        for _ in range(3):
            await w.apply_ops([w.with_state(lambda s: s.inc(w.actor_id))])
        r = await Core.open(make_opts(MemoryStorage(remote)))
        probes = []
        orig = r.storage.stat_ops

        async def counting(wanted):
            probes.append(wanted)
            return await orig(wanted)

        r.storage.stat_ops = counting
        await r.read_remote()
        assert probes == []  # sampled, but no storage probe
        status = r.last_replication_status
        assert status is not None
        assert status["backlog"] == {"files": 0, "bytes": 0, "per_actor": {}}
        # an explicit status call still probes for real
        await r.replication_status()
        assert len(probes) == 1

    run(go())
    g = trace.snapshot()["gauges"]
    assert g["repl_backlog_files"] == 0
    assert g["repl_backlog_bytes"] == 0
    trace.reset()


def test_repl_sample_opt_out(monkeypatch):
    monkeypatch.setenv("CRDT_REPL_SAMPLE", "0")
    trace.reset()

    async def go():
        w = await Core.open(make_opts(MemoryStorage(MemoryRemote())))
        await w.apply_ops([w.with_state(lambda s: s.inc(w.actor_id))])
        await w.compact()
        assert w.last_replication_status is None
        # the public API still works on demand — opt-out only silences
        # the automatic sampling
        st = await w.replication_status()
        assert st["backlog"]["files"] == 0

    run(go())
    assert "repl_samples" not in trace.snapshot()["counters"]
    trace.reset()


def test_compact_sink_record_carries_replication(tmp_path, monkeypatch):
    path = tmp_path / "dev.jsonl"
    sink.configure(str(path))
    try:
        async def go():
            w = await Core.open(make_opts(MemoryStorage(MemoryRemote())))
            for _ in range(2):
                await w.apply_ops(
                    [w.with_state(lambda s: s.inc(w.actor_id))]
                )
            await w.compact()
            return w

        w = run(go())
    finally:
        monkeypatch.setattr(sink, "_configured", False)
    rec = json.loads(path.read_text().splitlines()[-1])
    assert rec["schema"] == sink.SCHEMA_VERSION
    rep = rec["replication"]
    assert rep["actor"] == w.actor_id.hex()
    assert rep["local_clock"] == {w.actor_id.hex(): 2}
    assert rep["backlog"]["files"] == 0
    assert rep["checkpoint"]["sealed"] is True
    # and the file feeds straight into the fleet aggregator
    [summary] = fleet.device_summaries([str(path)])
    assert summary["replication"] == rep


def test_checkpoint_preserves_cursor_matrix():
    """A warm reopen keeps the replication view: the cursor matrix
    rides in the checkpoint, so watermark continuity survives restarts
    without re-reading any snapshot."""
    async def go():
        remote = MemoryRemote()
        a = await Core.open(make_opts(MemoryStorage(remote)))
        await a.apply_ops([a.with_state(lambda s: s.inc(a.actor_id))])
        await a.compact()
        storage_c = MemoryStorage(remote)
        c = await Core.open(make_opts(storage_c, checkpoint_on_read=True))
        await c.read_remote()  # learns matrix[A], reseals checkpoint
        before = await c.replication_status()
        assert before["matrix"] == {a.actor_id.hex(): {a.actor_id.hex(): 1}}
        c2 = await Core.open(make_opts(storage_c, checkpoint_on_read=True))
        assert c2.opened_from_checkpoint
        after = await c2.replication_status()
        assert after["matrix"] == before["matrix"]
        assert after["watermark"] == before["watermark"]

    run(go())


# ---- sink hardening: schema stamp + rotation ------------------------------


def test_check_schema_rejects_unknown_versions():
    sink.check_schema([{"schema": 1}, {"schema": 2}, {}])  # all readable
    with pytest.raises(sink.SinkSchemaError, match="record 2 has sink"):
        sink.check_schema([{"schema": 2}, {"schema": 99}], source="x.jsonl")
    with pytest.raises(sink.SinkSchemaError):
        sink.check_schema([{"schema": "2"}])  # stringly-typed → reject
    with pytest.raises(sink.SinkSchemaError):
        # bool is an int subclass and True == 1 — must not read as v1
        sink.check_schema([{"schema": True}])


def test_sink_rotation_bounds_file(tmp_path, monkeypatch):
    trace.reset()  # small records: the 500-byte cap must exceed one line
    path = tmp_path / "rot.jsonl"
    s = sink.MetricsSink(str(path))
    monkeypatch.setenv(sink.ENV_MAX_MB, "0.0005")  # 500 bytes
    for i in range(20):
        s.write(f"r{i}")
    assert path.stat().st_size <= 500
    rotated = tmp_path / "rot.jsonl.1"
    assert rotated.exists() and rotated.stat().st_size <= 500
    # every surviving record parses; labels continue across the seam
    recs = sink.read_records(str(rotated)) + sink.read_records(str(path))
    labels = [r["label"] for r in recs]
    assert labels == sorted(labels, key=lambda x: int(x[1:]))
    assert labels[-1] == "r19"
    # off by default: unset → no rotation however large the file
    monkeypatch.delenv(sink.ENV_MAX_MB)
    big = sink.MetricsSink(str(tmp_path / "big.jsonl"))
    for i in range(20):
        big.write(f"b{i}")
    assert not (tmp_path / "big.jsonl.1").exists()


def test_to_prometheus_timestamp_and_help(tmp_path):
    trace.reset()
    trace.add("ops_folded", 3)
    trace.gauge("stream_producers", 2)
    out = sink.to_prometheus(timestamp=1700000000.5)
    trace.reset()
    assert "crdt_ops_folded_total 3 1700000000500" in out
    assert "crdt_stream_producers 2 1700000000500" in out
    # HELP text is pulled from the registry tables in the docs
    help_ = sink.registry_help()
    assert "ops_folded" in help_ and "per-op path" in help_["ops_folded"]
    assert "# HELP crdt_ops_folded_total " + help_["ops_folded"] in out


# ---- fleet aggregation ----------------------------------------------------


def _dev_record(actor, local, union, files, nbytes, wm_lag, ts=100.0,
                remote=RID):
    return {
        "schema": 2, "label": "compact", "ts": ts,
        "spans": {}, "counters": {}, "gauges": {},
        "replication": {
            "actor": actor.hex(),
            "remote_id": remote.hex(),
            "local_clock": {k.hex(): v for k, v in local.items()},
            "union_clock": {k.hex(): v for k, v in union.items()},
            "watermark": {}, "matrix": {},
            "backlog": {"files": files, "bytes": nbytes, "per_actor": {}},
            "divergence": {
                "actors_behind": 0, "version_lag": 0,
                "watermark_lag": wm_lag, "known_replicas": 2,
            },
            "checkpoint": {
                "enabled": True, "sealed": True, "staleness_versions": 0,
            },
        },
    }


def _write_jsonl(path, records):
    path.write_text("".join(json.dumps(r) + "\n" for r in records))


def test_fleet_report_watermark_and_lag_distribution(tmp_path):
    pa, pb = tmp_path / "a.jsonl", tmp_path / "b.jsonl"
    _write_jsonl(pa, [_dev_record(A, {A: 3, B: 2}, {A: 3, B: 2}, 0, 0, 0)])
    _write_jsonl(pb, [
        # an older record first — the NEWEST replication payload wins
        _dev_record(B, {A: 1}, {A: 1}, 0, 0, 0, ts=50.0),
        _dev_record(B, {A: 3}, {A: 3, B: 2}, 2, 410, 5, ts=150.0),
    ])
    report = fleet.fleet_report(
        fleet.device_summaries([str(pa), str(pb)])
    )
    assert report["n_devices"] == 2
    [r] = report["remotes"]
    assert r["remote_id"] == RID.hex()
    assert r["converged"] is False
    # fleet union {A:3,B:2}; stable watermark = pointwise min of local
    # clocks → A: min(3,3)=3, B: min(2,0)=0 → dropped
    assert r["union_clock"] == {A.hex(): 3, B.hex(): 2}
    assert r["stable_watermark"] == {A.hex(): 3}
    assert [d["lag"] for d in r["devices"]] == [0, 2]
    assert r["lag"] == {"min": 0, "p50": 0, "p99": 2, "max": 2}
    assert r["backlog_files"] == {"p50": 0, "p99": 2}
    assert r["backlog_bytes"] == {"p50": 0, "p99": 410}


def test_fleet_converged_fixed_point_and_remote_grouping(tmp_path):
    other = b"\x77" * 32
    paths = []
    for i, actor in enumerate((A, B)):
        p = tmp_path / f"dev{i}.jsonl"
        _write_jsonl(p, [
            _dev_record(actor, {A: 3, B: 2}, {A: 3, B: 2}, 0, 0, 0)
        ])
        paths.append(str(p))
    # a third device on a DIFFERENT remote must not average in
    p = tmp_path / "other.jsonl"
    _write_jsonl(p, [_dev_record(C, {C: 9}, {C: 9}, 0, 0, 0, remote=other)])
    paths.append(str(p))
    report = fleet.fleet_report(fleet.device_summaries(paths))
    assert [r["remote_id"] for r in report["remotes"]] == sorted(
        [other.hex(), RID.hex()]
    )
    main = next(r for r in report["remotes"] if r["remote_id"] == RID.hex())
    assert main["converged"] is True
    assert main["stable_watermark"] == {A.hex(): 3, B.hex(): 2}
    assert main["lag"] == {"min": 0, "p50": 0, "p99": 0, "max": 0}


def test_fleet_rejects_inputs_loudly(tmp_path):
    # no replication payload at all
    p = tmp_path / "plain.jsonl"
    _write_jsonl(p, [{"schema": 2, "label": "compact", "spans": {}}])
    with pytest.raises(fleet.FleetInputError, match="no record carries"):
        fleet.device_summaries([str(p)])
    # unreadable schema fails BEFORE any aggregation
    p2 = tmp_path / "future.jsonl"
    _write_jsonl(p2, [{"schema": 3, "replication": {}}])
    with pytest.raises(sink.SinkSchemaError):
        fleet.device_summaries([str(p2)])


def test_fleet_cli_end_to_end_two_real_devices(tmp_path, capsys,
                                               monkeypatch):
    """Two real cores compact into per-device sink files; `obs_report
    fleet` reports the true fleet watermark and lag."""
    from crdt_enc_tpu.tools import obs_report

    remote = MemoryRemote()
    pa, pb = tmp_path / "deva.jsonl", tmp_path / "devb.jsonl"

    async def device(path, n_ops, read_first):
        sink.configure(str(path))
        w = await Core.open(make_opts(MemoryStorage(remote)))
        if read_first:
            await w.read_remote()
        for _ in range(n_ops):
            await w.apply_ops([w.with_state(lambda s: s.inc(w.actor_id))])
        await w.compact()
        return w

    try:
        a = run(device(pa, 3, False))
        b = run(device(pb, 2, True))
    finally:
        monkeypatch.setattr(sink, "_configured", False)
    assert obs_report.main(["fleet", str(pa), str(pb)]) == 0
    out = capsys.readouterr().out
    ah, bh = a.actor_id.hex(), b.actor_id.hex()
    # device A compacted before B wrote: fleet watermark = A's clock
    # min B's clock pointwise = {A:3}; A lags B's 2 unseen versions
    assert "# fleet: 2 device(s), 1 remote(s)" in out
    assert f"    {ah} = 3" in out
    assert f"device {ah}  lag=2" in out
    assert f"device {bh}  lag=0" in out
    # --json emits the structured report
    assert obs_report.main(["fleet", "--json", str(pa), str(pb)]) == 0
    rep = json.loads(capsys.readouterr().out)
    [r] = rep["remotes"]
    assert r["stable_watermark"] == {ah: 3}
    assert r["union_clock"] == {ah: 3, bh: 2}
    # a deviceless file exits 2 with a pointed message
    empty = tmp_path / "none.jsonl"
    _write_jsonl(empty, [{"schema": 2, "label": "x", "spans": {}}])
    assert obs_report.main(["fleet", str(empty)]) == 2
    assert "no record carries" in capsys.readouterr().err


def test_fleet_golden(capsys, monkeypatch):
    """The committed fixture files render byte-identically to the
    committed golden — the same diff tools/run_checks.sh runs (both
    pin the default SLO config: the SLO column deliberately follows
    CRDT_SLO_*, so the golden must not inherit ambient env)."""
    from crdt_enc_tpu.tools import obs_report

    monkeypatch.delenv("CRDT_SLO_FRESHNESS_LAG", raising=False)
    monkeypatch.delenv("CRDT_SLO_OBJECTIVE", raising=False)
    assert obs_report.main([
        "fleet",
        str(DATA / "fleet_device_a.jsonl"),
        str(DATA / "fleet_device_b.jsonl"),
    ]) == 0
    out = capsys.readouterr().out
    assert out == (DATA / "obs_fleet_golden.txt").read_text()


# ---- bench trend + regression gate ----------------------------------------


def _bench(metric, value, ts, shape=None, backend="cpu"):
    return {
        "metric": metric, "value": value, "ts": ts, "unit": "ops/s",
        "backend": backend, "shape": shape or {"n": 1000},
        "best_variant": "v",
    }


def test_bench_trend_trajectory_and_regressions():
    records = [
        _bench("fold", 100.0, "t1"),
        _bench("fold", 120.0, "t2"),
        _bench("fold", 90.0, "t3"),
        _bench("fold", 500.0, "t1", shape={"n": 9}),  # separate config
        _bench("merge", 50.0, "t1"),                  # single run
        {"schema": 2, "label": "compact", "spans": {}},  # sink noise
    ]
    trend = fleet.bench_trend(records)
    by = {(c["metric"], json.dumps(c["shape"], sort_keys=True)): c
          for c in trend}
    fold = by[("fold", '{"n": 1000}')]
    assert [r["value"] for r in fold["runs"]] == [100.0, 120.0, 90.0]
    assert fold["latest"] == 90.0 and fold["prior_best"] == 120.0
    assert fold["latest_vs_prior_best_pct"] == -25.0
    assert "prior_best" not in by[("merge", '{"n": 1000}')]
    assert by[("fold", '{"n": 9}')]["latest"] == 500.0
    # regression gate: -25% flags at 10, passes at 30; single-run and
    # single-config-improved never flag
    assert [c["metric"] for c in fleet.trend_regressions(trend, 10)] == [
        "fold"
    ]
    assert fleet.trend_regressions(trend, 30) == []
    # metric filter narrows the table
    only = fleet.bench_trend(records, metric="merge")
    assert [c["metric"] for c in only] == ["merge"]


def test_bench_trend_shapeless_records_key_by_config():
    """Shapeless records (the sim bench) fall back to their config
    string — a 4r×50s and an 8r×250s sim run are different workloads
    and must not collapse into one regression trajectory (the ISSUE-11
    ratchet would otherwise compare apples to oranges)."""
    records = [
        {"metric": "sim_schedules_per_sec", "value": 1.3, "ts": "t1",
         "backend": "cpu", "config": "sim_4r_50s_all"},
        {"metric": "sim_schedules_per_sec", "value": 0.5, "ts": "t2",
         "backend": "cpu", "config": "sim_8r_250s_all"},
    ]
    trend = fleet.bench_trend(records)
    assert len(trend) == 2
    assert sorted(c["shape"]["config"] for c in trend) == [
        "sim_4r_50s_all", "sim_8r_250s_all",
    ]
    # one run each → no trajectory, no false regression
    assert fleet.trend_regressions(trend, 10) == []
    # the committed BENCH_LOCAL passes the run_checks.sh ratchet at 45%
    repo_records = sink.read_records(
        str(pathlib.Path(__file__).parent.parent / "BENCH_LOCAL.jsonl")
    )
    repo_trend = fleet.bench_trend(repo_records)
    assert fleet.trend_regressions(repo_trend, 45) == []


def test_trend_cli_fail_on_regression(tmp_path, capsys):
    from crdt_enc_tpu.tools import obs_report

    p = tmp_path / "bench.jsonl"
    _write_jsonl(p, [
        _bench("fold", 100.0, "t1"), _bench("fold", 80.0, "t2"),
    ])
    assert obs_report.main(["trend", str(p)]) == 0
    out = capsys.readouterr().out
    assert "-20.00%" in out and "REGRESSION" not in out
    assert obs_report.main(["trend", str(p), "--fail-on-regression", "10"]
                           ) == 1
    cap = capsys.readouterr()
    assert "** REGRESSION **" in cap.out
    assert "1 config(s) regressed" in cap.err
    assert obs_report.main(["trend", str(p), "--fail-on-regression", "25"]
                           ) == 0
    capsys.readouterr()
    # mixed-version input fails loudly, exit 2
    bad = tmp_path / "bad.jsonl"
    _write_jsonl(bad, [_bench("fold", 1.0, "t1"), {"schema": 42}])
    assert obs_report.main(["trend", str(bad)]) == 2
    assert "sink schema 42" in capsys.readouterr().err
    # the repo's own BENCH_LOCAL.jsonl parses (real-shape regression)
    bench_local = pathlib.Path(__file__).parent.parent / "BENCH_LOCAL.jsonl"
    if bench_local.exists():
        assert obs_report.main(["trend", str(bench_local)]) == 0
        assert "orset" in capsys.readouterr().out

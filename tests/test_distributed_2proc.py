"""Real 2-process ``jax.distributed`` run (VERDICT r3 item 5).

Spawns two worker processes that rendezvous through a localhost
coordinator on the CPU backend, build the multihost (dp=hosts, mp=chips)
mesh, assemble a ``global_op_batch`` from disjoint per-process rows, fold
sharded, and verify against the single-device fold.  This executes the
``jax.process_count() > 1`` branches of parallel/distributed.py —
DCN bootstrap, ``make_array_from_process_local_data`` assembly, the
ragged-row allgather — with actual process boundaries, which the
in-process tests (test_distributed.py) can only fake.

Reference scale-out contract: SURVEY.md §2.3.
"""

from __future__ import annotations

import os
import socket
import subprocess
import sys

import pytest

_WORKER = os.path.join(os.path.dirname(__file__), "_dist_worker.py")
_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# Environment guard: some jaxlib builds cannot run 2-process collectives
# on the CPU backend at all ("Multiprocess computations aren't
# implemented on the CPU backend") — a capability gap of the box, not a
# regression in this repo's distributed layer.  Those runs SKIP with the
# exact backend message; any other worker failure still fails the test.
_ENV_SKIP_MARKERS = (
    "Multiprocess computations aren't implemented on the CPU backend",
    "multiprocess computations aren't implemented",
)


def _skip_if_env_limited(out: str, err: str) -> None:
    for marker in _ENV_SKIP_MARKERS:
        if marker.lower() in (out + err).lower():
            pytest.skip(
                "2-proc jax.distributed unavailable on this box: "
                f"jaxlib reports {marker!r}"
            )


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _run_workers(extra_args=(), timeout=300):
    port = _free_port()
    env = os.environ.copy()
    # a wedged TPU tunnel must not hang the workers at interpreter start
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env.pop("PJRT_LIBRARY_PATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["PYTHONPATH"] = _REPO + os.pathsep + env.get("PYTHONPATH", "")
    procs = [
        subprocess.Popen(
            [sys.executable, _WORKER, str(rank), str(port), *extra_args],
            env=env,
            stdout=subprocess.PIPE,
            stderr=subprocess.PIPE,
            text=True,
        )
        for rank in (0, 1)
    ]
    outs = []
    try:
        for p in procs:
            outs.append(p.communicate(timeout=timeout))
    finally:
        for p in procs:
            p.kill()
    for rank, (p, (out, err)) in enumerate(zip(procs, outs)):
        if p.returncode != 0:
            _skip_if_env_limited(out, err)
        assert p.returncode == 0, (
            f"rank {rank} exited {p.returncode}\nstdout:\n{out}\n"
            f"stderr:\n{err}"
        )
        assert f"DIST_OK rank={rank}" in out, (rank, out, err)


def test_two_process_fold():
    _run_workers()


def test_two_process_core_lifecycle(tmp_path):
    """VERDICT r4 item 6: the full Core lifecycle — write, mesh-ingest,
    convergence checks, CONCURRENT compaction, post-compact read — across
    2 real jax.distributed processes sharing one fs remote."""
    _run_workers(["lifecycle", str(tmp_path)], timeout=600)

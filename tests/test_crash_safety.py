"""Fault-injection tests for the crash-safety-by-ordering design.

The reference has no fault tests (SURVEY.md §5); its safety story is
structural — immutable ``create_new`` + fsync writes, content-addressed
names, store-new-before-delete-old (crdt-enc-tokio lib.rs:326-432, core
lib.rs:362-369, 653-661).  These tests *prove* the structure: a simulated
process death at every dangerous point between a durable write and its
follow-up must leave the remote in a state every replica still converges
from, and a re-run must clean up rather than corrupt.

``CrashStorage`` wraps a real backend and raises ``SimulatedCrash`` when a
named method is hit — before the call (the write never happened) or after
it (the write is durable but the caller's bookkeeping is lost), which is
exactly the fault model of a kill -9 between two syscalls.
"""

import asyncio

import pytest

from crdt_enc_tpu.backends import FsStorage, IdentityCryptor, PlainKeyCryptor
from crdt_enc_tpu.core import Core, OpenOptions, gcounter_adapter, orset_adapter
from crdt_enc_tpu.models import canonical_bytes
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


class SimulatedCrash(Exception):
    pass


class CrashStorage:
    """Delegate to ``inner``, but die at an injection point.

    ``crash_on``: method name; ``when``: "before" (call never runs) or
    "after" (call completes — its effects are durable — then we die);
    ``skip``: let that many calls through first.  The trap disarms after
    firing once, modelling a process that restarts and does not crash
    again at the same point.
    """

    def __init__(self, inner, crash_on: str, when: str = "before", skip: int = 0):
        assert when in ("before", "after")
        self._inner = inner
        self._crash_on = crash_on
        self._when = when
        self._remaining = skip
        self.armed = True

    def __getattr__(self, name):
        attr = getattr(self._inner, name)
        if name != self._crash_on or not callable(attr):
            return attr

        async def trapped(*args, **kwargs):
            if not self.armed:
                return await attr(*args, **kwargs)
            if self._remaining > 0:
                self._remaining -= 1
                return await attr(*args, **kwargs)
            self.armed = False
            if self._when == "before":
                raise SimulatedCrash(f"crash before {name}")
            result = await attr(*args, **kwargs)
            raise SimulatedCrash(f"crash after {name}")

        return trapped


def run(coro):
    return asyncio.run(coro)


def make_opts(storage, adapter, create=True):
    return OpenOptions(
        storage=storage,
        cryptor=IdentityCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=adapter,
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=create,
    )


@pytest.fixture
def fs_factory(tmp_path):
    remote_dir = tmp_path / "remote"
    counter = iter(range(1000))
    return lambda: FsStorage(str(tmp_path / f"local{next(counter)}"), str(remote_dir))


async def _seed_orset(fs_factory):
    """One replica writes a few ops; returns its canonical state bytes."""
    c = await Core.open(make_opts(fs_factory(), orset_adapter()))
    for m in (b"a", b"b", b"c"):
        await c.update(lambda s, m=m: s.add_ctx(c.actor_id, m))
    await c.update(lambda s: s.rm_ctx(b"b"))
    return c.with_state(canonical_bytes)


def test_crash_between_snapshot_write_and_state_gc(fs_factory):
    """Die after the new snapshot is durable but before old states are
    removed: both snapshots remain; readers merge them (idempotent) and a
    re-run of compact finishes the GC."""

    async def go():
        await _seed_orset(fs_factory)
        # first compaction succeeds → one state file exists
        c1 = await Core.open(make_opts(fs_factory(), orset_adapter()))
        await c1.read_remote()
        await c1.compact()
        await c1.update(lambda s: s.add_ctx(c1.actor_id, b"d"))

        crashy = CrashStorage(fs_factory(), "remove_states", when="before")
        c2 = await Core.open(make_opts(crashy, orset_adapter()))
        with pytest.raises(SimulatedCrash):
            await c2.compact()

        # remote now holds the old snapshot, the new snapshot, and
        # possibly op files remove_ops didn't get to — every combination
        # must fold to the same state.  Two independent readers of the
        # dirty remote must agree byte-for-byte (not just on membership —
        # clocks and dots must survive the crash intact too).
        c3 = await Core.open(make_opts(fs_factory(), orset_adapter()))
        await c3.read_remote()
        assert c3.with_state(lambda s: s.members()) == [b"a", b"c", b"d"]
        c3b = await Core.open(make_opts(fs_factory(), orset_adapter()))
        await c3b.read_remote()
        assert c3.with_state(canonical_bytes) == c3b.with_state(canonical_bytes)
        # ...and byte-identically to the writer that survived
        await c1.read_remote()
        assert c1.with_state(canonical_bytes) == c3.with_state(canonical_bytes)

        # re-running compact on a fresh replica completes the GC
        await c3.compact()
        clean = fs_factory()
        assert len(await clean.list_state_names()) == 1
        assert await clean.list_op_actors() == []

    run(go())


def test_crash_between_snapshot_write_and_op_gc(fs_factory):
    """Die before op GC: the snapshot and the op files it covers coexist.
    Readers fold the snapshot first, then skip the already-covered op
    versions via the concurrent-read tolerance (lib.rs:521-525 semantics)."""

    async def go():
        await _seed_orset(fs_factory)
        crashy = CrashStorage(fs_factory(), "remove_ops", when="before")
        c1 = await Core.open(make_opts(crashy, orset_adapter()))
        with pytest.raises(SimulatedCrash):
            await c1.compact()

        c2 = await Core.open(make_opts(fs_factory(), orset_adapter()))
        await c2.read_remote()
        assert c2.with_state(lambda s: s.members()) == [b"a", b"c"]
        # both the snapshot and the covered ops are present right now
        dirty = fs_factory()
        assert len(await dirty.list_state_names()) == 1
        assert len(await dirty.list_op_actors()) == 1

        await c2.compact()
        clean = fs_factory()
        assert await clean.list_op_actors() == []
        assert len(await clean.list_state_names()) == 1

    run(go())


def test_crash_in_meta_rewrite_leaves_mergeable_metas(fs_factory):
    """Die between storing the rewritten remote-meta and deleting the
    superseded files: multiple meta files remain, and because RemoteMeta is
    a CRDT they merge on the next read — the key material survives."""

    async def go():
        c1 = await Core.open(make_opts(fs_factory(), gcounter_adapter()))
        key1 = c1._data.keys.latest_key()
        assert key1 is not None

        # second replica's open rewrites meta (its read-notify-store cycle);
        # crash it between store and delete
        crashy = CrashStorage(fs_factory(), "remove_remote_metas", when="before")
        try:
            await Core.open(make_opts(crashy, gcounter_adapter()))
        except SimulatedCrash:
            pass

        dirty = fs_factory()
        assert len(await dirty.list_remote_meta_names()) >= 1

        c3 = await Core.open(make_opts(fs_factory(), gcounter_adapter()))
        key3 = c3._data.keys.latest_key()
        assert key3 is not None
        assert key3.id == key1.id and key3.material == key1.material

    run(go())


def test_crash_after_op_write_before_cursor_update(fs_factory, tmp_path):
    """Die after the op file is durable but before the producer cursor is
    persisted: on restart the replica must (a) recover the op's effect via
    read_remote and (b) place its next write past the leaked file by
    collision probing — never clobber it."""

    async def go():
        local = str(tmp_path / "producer")
        remote = str(tmp_path / "remote")

        crashy = CrashStorage(
            FsStorage(local, remote), "store_local_meta", when="before",
            # skip the two open-time local-meta writes (replica
            # identity + the key-mint last_key_dot cursor) so the
            # crash lands on the producer-cursor persist in update
            skip=2
        )
        c1 = await Core.open(make_opts(crashy, gcounter_adapter()))
        actor = c1.actor_id
        with pytest.raises(SimulatedCrash):
            await c1.update(lambda s: s.inc(actor, 5))
        # the op file is durable; the cursor write never happened

        # restart the same replica (same local dir)
        c2 = await Core.open(
            make_opts(FsStorage(local, remote), gcounter_adapter(), create=False)
        )
        assert c2.actor_id == actor
        await c2.read_remote()  # recovers the leaked op's effect
        assert c2.with_state(lambda s: s.read()) == 5
        await c2.update(lambda s: s.inc(actor, 7))

        # an independent reader sees both increments, no gaps, no clobber
        c3 = await Core.open(
            make_opts(FsStorage(str(tmp_path / "reader"), remote), gcounter_adapter())
        )
        await c3.read_remote()
        assert c3.with_state(lambda s: s.read()) == 12

    run(go())


def test_restart_without_read_remote_probes_past_leaked_file(fs_factory, tmp_path):
    """Same fault as above, but the restarted replica writes immediately
    (no explicit read_remote): the durable cursor never recorded the
    leaked v1, so only storage can reveal it.  Since the dot-reuse fix
    (``Core._ensure_own_history``, simulator-discovered:
    tests/data/sim/dot_reuse_crash_reopen.json), the first write of an
    incarnation probes its own op tail, finds the orphan, and ingests
    it BEFORE deriving the new op — so the new op lands at v2 (never
    clobbering v1), carries a fresh dot (no overlap with the leaked
    op's), and the crashed increment survives: readers converge to
    5 + 7 = 12, not to a max-masked 7."""

    async def go():
        local = str(tmp_path / "producer")
        remote = str(tmp_path / "remote")

        crashy = CrashStorage(
            FsStorage(local, remote), "store_local_meta", when="before",
            # skip the two open-time local-meta writes (replica
            # identity + the key-mint last_key_dot cursor) so the
            # crash lands on the producer-cursor persist in update
            skip=2
        )
        c1 = await Core.open(make_opts(crashy, gcounter_adapter()))
        actor = c1.actor_id
        with pytest.raises(SimulatedCrash):
            await c1.update(lambda s: s.inc(actor, 5))

        c2 = await Core.open(
            make_opts(FsStorage(local, remote), gcounter_adapter(), create=False)
        )
        await c2.update(lambda s: s.inc(actor, 7))  # own-tail probe found v1
        assert c2.with_state(lambda s: s.read()) == 12

        # both op files exist: the leaked v1 was not clobbered
        dirty = FsStorage(str(tmp_path / "probe-local"), remote)
        files = await dirty.load_ops([(actor, 1)])
        assert [v for _, v, _ in files] == [1, 2]

        c3 = await Core.open(
            make_opts(FsStorage(str(tmp_path / "reader"), remote), gcounter_adapter())
        )
        await c3.read_remote()
        assert c3.with_state(lambda s: s.read()) == 12

    run(go())


def test_torn_tmp_files_are_invisible(fs_factory, tmp_path):
    """A crash mid-write leaves only ``.tmp-*`` files (tmp+fsync+link
    publish).  Listings, op scans, and opens must not see them."""

    async def go():
        await _seed_orset(fs_factory)
        remote = tmp_path / "remote"
        # simulate torn writes in every remote family (states/ may not exist
        # yet — no compaction has run — exactly like a crash mid-first-write)
        (remote / "states").mkdir(exist_ok=True)
        (remote / "states" / ".tmp-dead").write_bytes(b"\x00garbage")
        (remote / "meta" / ".tmp-dead").write_bytes(b"\x00garbage")
        ops_dirs = list((remote / "ops").iterdir())
        (ops_dirs[0] / ".tmp-dead").write_bytes(b"\x00garbage")

        c = await Core.open(make_opts(fs_factory(), orset_adapter()))
        await c.read_remote()
        assert c.with_state(lambda s: s.members()) == [b"a", b"c"]
        await c.compact()  # GC also tolerates the junk
        c2 = await Core.open(make_opts(fs_factory(), orset_adapter()))
        await c2.read_remote()
        assert c2.with_state(lambda s: s.members()) == [b"a", b"c"]

    run(go())


def test_interrupted_compact_is_idempotent_under_retry(fs_factory):
    """Run compact repeatedly with a crash at a different point each time;
    the remote must remain convergent throughout and end clean."""

    async def go():
        await _seed_orset(fs_factory)
        for point, when in [
            ("store_state", "before"),
            ("store_state", "after"),
            ("remove_states", "before"),
            ("remove_ops", "before"),
        ]:
            crashy = CrashStorage(fs_factory(), point, when=when)
            c = await Core.open(make_opts(crashy, orset_adapter()))
            with pytest.raises(SimulatedCrash):
                await c.compact()
            probe = await Core.open(make_opts(fs_factory(), orset_adapter()))
            await probe.read_remote()
            assert probe.with_state(lambda s: s.members()) == [b"a", b"c"]

        final = await Core.open(make_opts(fs_factory(), orset_adapter()))
        await final.compact()
        clean = fs_factory()
        assert len(await clean.list_state_names()) == 1
        assert await clean.list_op_actors() == []

    run(go())

"""Always-on fleet daemon (ISSUE 12): scheduler, backoff/quarantine,
breaker, admission, drain, crash/reopen.

The control-plane contract under test: compaction cadence is driven by
STALENESS (backlog/watermark), failing tenants isolate into capped
backoff and a quarantine ring instead of poisoning the cycle, a
whole-cycle outage trips the circuit breaker into honest degraded mode,
the fleet mutates (admit/evict) while running, and nothing the daemon
does — including being SIGKILL'd mid-flight — can diverge a tenant from
what a solo ``Core.compact()`` of the same remote produces.
"""

import asyncio
import copy
import json
import urllib.request

import pytest

from crdt_enc_tpu.backends import (
    FsStorage,
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PlainKeyCryptor,
)
from crdt_enc_tpu.core import Core, OpenOptions, StaleWriterError, orset_adapter
from crdt_enc_tpu.models import canonical_bytes
from crdt_enc_tpu.parallel import TpuAccelerator
from crdt_enc_tpu.serve import (
    AdmissionError,
    DaemonConfig,
    FleetDaemon,
    ServeConfig,
)
from crdt_enc_tpu.serve.daemon import ACTIVE, BACKOFF, QUARANTINED
from crdt_enc_tpu.utils import trace
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


def run(coro):
    return asyncio.run(coro)


def make_opts(storage, create=True, **kw):
    kw.setdefault("accelerator", TpuAccelerator(min_device_batch=1))
    return OpenOptions(
        storage=storage,
        cryptor=IdentityCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=orset_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=create,
        **kw,
    )


async def seed_tenant(storage, n_ops, tag):
    """Populate a tenant remote with adds through a writer core."""
    core = await Core.open(make_opts(storage))
    for i in range(n_ops):
        m = b"%s-%d" % (tag, i % 13)
        await core.update(lambda s, m=m: s.add_ctx(core.actor_id, m))
    return core


class FlakyStorage(MemoryStorage):
    """Remote that refuses listings while ``broken`` — the transient
    storage-outage class the backoff machine exists for."""

    broken = False

    async def list_op_actors(self):
        if self.broken:
            raise OSError("injected outage")
        return await super().list_op_actors()


def quick_cfg(**kw):
    kw.setdefault("max_idle_cycles", 1)
    kw.setdefault("backoff_base", 1.0)
    kw.setdefault("backoff_cap", 2.0)
    kw.setdefault("backoff_jitter", 0.0)
    kw.setdefault("serve", ServeConfig(seal_empty=False))
    return DaemonConfig(**kw)


# ---------------------------------------------------------- scheduling


def test_scheduler_compacts_backlog_polls_quiet():
    """Staleness-driven cadence: a tenant with sealed-but-unfolded ops
    is selected and sealed; an in-sync tenant is only stat-polled (no
    seal attempt, no decrypt) until its idle cadence comes due."""

    async def scenario():
        busy_r, quiet_r = MemoryRemote(), MemoryRemote()
        await seed_tenant(MemoryStorage(busy_r), 20, b"busy")
        busy = await Core.open(make_opts(MemoryStorage(busy_r)))
        quiet = await Core.open(make_opts(MemoryStorage(quiet_r)))
        await quiet.compact()  # in sync: no backlog, no staleness
        daemon = FleetDaemon(
            [busy, quiet], quick_cfg(max_idle_cycles=100)
        )
        report = await daemon.run_cycle()
        assert "t0" in report["selected"]
        assert report["results"]["t0"]["outcome"] == "sealed"
        # never-sealed tenants are due once (unknown staleness); from
        # the second cycle the quiet tenant is poll-only
        report2 = await daemon.run_cycle()
        assert report2["selected"] == []
        assert report2["results"]["t0"]["outcome"] == "polled"
        assert report2["results"]["t1"]["outcome"] == "polled"
        # laggards jump the queue: new ops land on the busy tenant and
        # the next cycle selects exactly it
        w = await Core.open(make_opts(MemoryStorage(busy_r)))
        await w.update(lambda s: s.add_ctx(w.actor_id, b"late"))
        await daemon.run_cycle()  # poll refreshes the staleness inputs
        report3 = await daemon.run_cycle()
        assert report3["selected"] == ["t0"]
        await daemon.drain()

    run(scenario())


# ------------------------------------------- backoff/quarantine machine


def test_backoff_quarantine_and_recovery():
    """Consecutive failures walk active → backoff → quarantined; the
    ring re-probes on its cadence and a healed tenant returns to
    sealing.  Healthy tenants keep sealing throughout."""

    async def scenario():
        bad_r, ok_r = MemoryRemote(), MemoryRemote()
        await seed_tenant(FlakyStorage(bad_r), 15, b"bad")
        await seed_tenant(MemoryStorage(ok_r), 15, b"ok")
        bad_storage = FlakyStorage(bad_r)
        bad = await Core.open(make_opts(bad_storage))
        ok = await Core.open(make_opts(MemoryStorage(ok_r)))
        daemon = FleetDaemon(
            [bad, ok],
            quick_cfg(
                quarantine_after=2, quarantine_probe_every=2,
                backoff_base=2.0, backoff_cap=4.0,
            ),
        )
        bad_storage.broken = True
        trace.reset()
        await daemon.run_cycle()  # failure 1 → backoff
        t0 = daemon.entry("t0")
        assert t0.state == BACKOFF and t0.failures == 1
        assert t0.eligible_at > daemon.cycle
        await daemon.run_cycle()  # still backing off: not attempted
        assert t0.state == BACKOFF
        await daemon.run_cycle()  # re-probe → failure 2 → quarantine
        assert t0.state == QUARANTINED
        snap = trace.snapshot()
        assert snap["counters"]["daemon_backoffs"] >= 1
        assert snap["counters"]["daemon_quarantines"] == 1
        assert snap["gauges"]["daemon_quarantined"] == 1
        # the healthy tenant sealed in cycle 1 and stayed active
        assert daemon.entry("t1").state == ACTIVE
        assert daemon.entry("t1").last_sealed >= 1
        # heal → the ring's slow re-probe path recovers the tenant
        bad_storage.broken = False
        for _ in range(6):
            await daemon.run_cycle()
            if daemon.entry("t0").state == ACTIVE:
                break
        assert daemon.entry("t0").state == ACTIVE
        assert trace.snapshot()["gauges"]["daemon_quarantined"] == 0
        await daemon.drain()

    run(scenario())


def test_quarantine_probe_runs_even_when_not_due():
    """The ring's re-probe cadence is a guarantee: a quarantined tenant
    whose last status looks healthy (not _due, huge idle cadence) must
    still be attempted every ``quarantine_probe_every`` cycles — and
    recover once its storage heals."""

    async def scenario():
        remote = MemoryRemote()
        await seed_tenant(FlakyStorage(remote), 12, b"q")
        st = FlakyStorage(remote)
        core = await Core.open(make_opts(st))
        daemon = FleetDaemon(
            [core],
            quick_cfg(
                max_idle_cycles=1000, quarantine_after=2,
                quarantine_probe_every=2, backoff_cap=1.0,
            ),
        )
        await daemon.run_cycle()  # seals; status now healthy
        assert daemon.entry("t0").last_sealed == 1
        st.broken = True
        while daemon.entry("t0").state != QUARANTINED:
            await daemon.run_cycle()
            assert daemon.cycle < 10
        st.broken = False
        trace.reset()
        while daemon.entry("t0").state != ACTIVE:
            await daemon.run_cycle()
            assert daemon.cycle < 16, "quarantine probe never ran"
        assert trace.snapshot()["counters"]["daemon_probes"] >= 1
        await daemon.drain()

    run(scenario())


def test_circuit_breaker_degraded_and_half_open_recovery():
    """Whole-cycle failures trip the breaker: degraded mode seals
    nothing (no decrypt/decode attempts beyond the half-open probe),
    reports honestly, and closes again when the probe succeeds."""

    async def scenario():
        remotes = [MemoryRemote() for _ in range(2)]
        storages = []
        cores = []
        for r in remotes:
            await seed_tenant(FlakyStorage(r), 12, b"x")
            st = FlakyStorage(r)
            storages.append(st)
            cores.append(await Core.open(make_opts(st)))
        daemon = FleetDaemon(
            cores,
            quick_cfg(
                quarantine_after=2,  # the whole fleet parks while open
                breaker_after=2, breaker_probe_every=2,
                backoff_cap=1.0,
            ),
        )
        for st in storages:
            st.broken = True
        trace.reset()
        while not daemon.degraded:
            report = await daemon.run_cycle()
            assert daemon.cycle < 20, "breaker never tripped"
        assert trace.snapshot()["counters"]["daemon_breaker_trips"] == 1
        assert daemon.health()["degraded"] is True
        # drive the fleet fully into quarantine while degraded: the
        # half-open probe must still find a tenant to try
        while any(
            daemon.entry(t).state != QUARANTINED for t in daemon.tenant_ids
        ):
            await daemon.run_cycle()
            assert daemon.cycle < 30, "fleet never fully parked"
        # degraded: polls only (errors recorded, nothing sealed) until
        # the half-open probe; heal and let the probe close the breaker
        for st in storages:
            st.broken = False
        while daemon.degraded:
            report = await daemon.run_cycle()
            assert daemon.cycle < 30, "breaker never closed"
        assert any(
            r["outcome"] == "sealed" for r in report["results"].values()
        )
        h = daemon.health()
        assert h["degraded"] is False
        await daemon.drain()

    run(scenario())


# --------------------------------------------------- admission/eviction


def test_admission_budget_and_eviction_checkpoint():
    async def scenario():
        remote = MemoryRemote()
        await seed_tenant(MemoryStorage(remote), 25, b"adm")
        storage = MemoryStorage(remote)
        core = await Core.open(make_opts(storage))
        daemon = FleetDaemon([core], quick_cfg())
        # fleet-size gate
        daemon.config.max_tenants = 1
        extra = await Core.open(make_opts(MemoryStorage(MemoryRemote())))
        with pytest.raises(AdmissionError):
            await daemon.admit(extra)
        # byte-budget gate: per-tenant estimate past the warm budget
        daemon.config.max_tenants = 100
        daemon.config.admission_bytes = 1024
        daemon.config.tenant_cost_bytes = 4096
        with pytest.raises(AdmissionError):
            await daemon.admit(extra)
        daemon.config.admission_bytes = 0  # back to the warm budget
        tid = await daemon.admit(extra)
        assert daemon.entry(tid) is not None
        # duplicate tid is refused loudly
        with pytest.raises(AdmissionError):
            await daemon.admit(extra, tid=tid)
        await daemon.run_cycle()
        # eviction checkpoints and hands the core back; the next open
        # of that tenant is WARM
        got = await daemon.evict("t0")
        assert got is core
        assert daemon.entry("t0") is None
        reopened = await Core.open(make_opts(storage, create=False))
        assert reopened.opened_from_checkpoint, (
            reopened.checkpoint_fallback_reason
        )
        assert reopened.with_state(canonical_bytes) == core.with_state(
            canonical_bytes
        )
        with pytest.raises(KeyError):
            await daemon.evict("t0")
        await daemon.discard("t0")  # unknown tid: cleanup path, safe
        await daemon.drain()

    run(scenario())


def test_drain_is_terminal_and_idempotent():
    async def scenario():
        remote = MemoryRemote()
        await seed_tenant(MemoryStorage(remote), 10, b"dr")
        storage = MemoryStorage(remote)
        core = await Core.open(make_opts(storage))
        daemon = FleetDaemon([core], quick_cfg())
        await daemon.run_cycle()
        assert (await daemon.drain()) == {}
        assert daemon.state == "drained"
        assert daemon.service.closed
        # drained daemon: cycles and admissions refuse loudly, a second
        # drain is a no-op
        with pytest.raises(RuntimeError):
            await daemon.run_cycle()
        with pytest.raises(AdmissionError):
            await daemon.admit(core, tid="again")
        assert (await daemon.drain()) == {}
        # the drain checkpoint makes the tenant's next open warm
        reopened = await Core.open(make_opts(storage, create=False))
        assert reopened.opened_from_checkpoint

    run(scenario())


# ------------------------------------------------------------- healthz


def test_healthz_daemon_section():
    async def scenario():
        remote = MemoryRemote()
        await seed_tenant(MemoryStorage(remote), 10, b"hz")
        core = await Core.open(make_opts(MemoryStorage(remote)))
        daemon = FleetDaemon([core], quick_cfg(), live_port=0)
        try:
            await daemon.run_cycle()
            port = daemon.service.live.port
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=5
            ) as resp:
                health = json.loads(resp.read())
            d = health["daemon"]
            assert d["state"] == "running"
            assert d["cycles"] == 1 and d["tenants"] == 1
            assert d["quarantined"] == 0 and d["degraded"] is False
            assert d["uptime_s"] >= 0
            assert d["last_cycle"]["selected"] == 1
        finally:
            await daemon.drain()
        assert daemon.health()["state"] == "drained"

    run(scenario())


# ------------------------------------------------- crash/reopen (kill)


@pytest.mark.parametrize("backend", ["memory", "fs"])
def test_sigkill_reopen_converges_warm_and_fsck_clean(backend, tmp_path):
    """Satellite 3: a daemon SIGKILL'd mid-flight (abandoned with no
    drain) loses nothing durable — every tenant reopens WARM from the
    cycle-sealed checkpoint, a post-reopen write mints fresh dots (the
    ``_ensure_own_history`` recovery contract), the fleet converges
    byte-identically with a cold oracle, and both backends' remotes
    fsck clean."""

    async def scenario():
        from crdt_enc_tpu.sim import DeterministicCryptor
        from crdt_enc_tpu.tools.fsck import fsck_remote

        def storage(i, tag):
            if backend == "memory":
                return MemoryStorage(remotes[i])
            return FsStorage(
                str(tmp_path / f"{tag}-{i}"), str(tmp_path / f"remote-{i}")
            )

        if backend == "memory":
            remotes = [MemoryRemote() for _ in range(3)]
        else:
            remotes = list(range(3))
        writers = [
            await seed_tenant(storage(i, "w"), 18, b"k%d" % i)
            for i in range(3)
        ]
        tenant_storages = [storage(i, "t") for i in range(3)]
        cores = [await Core.open(make_opts(st)) for st in tenant_storages]
        daemon = FleetDaemon(cores, quick_cfg())
        await daemon.run_cycle()  # seals snapshots + checkpoints
        # SIGKILL: no drain, no close — everything in memory abandoned
        del daemon, cores

        reopened = []
        for st in tenant_storages:
            c = await Core.open(make_opts(st, create=False))
            assert c.opened_from_checkpoint, c.checkpoint_fallback_reason
            reopened.append(c)
        # post-reopen writes go through the own-history guard and mint
        # fresh dots; a StaleWriterError here would be the documented
        # loud-transient (it must NOT corrupt) — with a healthy remote
        # it must simply succeed
        for i, c in enumerate(reopened):
            await c.update(
                lambda s, i=i: s.add_ctx(c.actor_id, b"post-kill-%d" % i)
            )
            await c.compact()
        for i, c in enumerate(reopened):
            cold = await Core.open(make_opts(storage(i, "cold")))
            await cold.read_remote()
            assert cold.with_state(canonical_bytes) == c.with_state(
                canonical_bytes
            ), f"tenant {i} diverged after kill/reopen"
            report = await fsck_remote(
                storage(i, "fsck"), DeterministicCryptor(f"k{i}"),
                PlainKeyCryptor(), deep=True,
            )
            assert report.ok, report.issues[:3]

    run(scenario())


def test_gc_orphan_dot_reuse_guard():
    """Regression for the simulator-discovered peer-GC blind spot
    (tests/data/sim/dot_reuse_gc_orphan.json): an op file a crashed
    incarnation stored but never recorded is folded AND GC'd by a peer
    before the author's first post-reopen write.  The author's own-tail
    probe finds nothing — the unread covering snapshot must force a
    re-read, so the next write mints a FRESH dot instead of reusing the
    spent one."""

    async def scenario():
        remote = MemoryRemote()
        storage = MemoryStorage(remote)
        w = await Core.open(make_opts(storage))
        for i in range(3):
            await w.update(
                lambda s, i=i: s.add_ctx(w.actor_id, b"m%d" % i)
            )
        await w.compact()  # snapshot + checkpoint; cursor v3
        # crash orphan: the op file lands, local meta/memory never learn
        blob = await w._seal([[0, b"orphan", [w.actor_id, 4]]])
        await w.storage.store_ops(w.actor_id, 4, blob)
        actor = w.actor_id
        # a peer folds the orphan and GCs it
        peer = await Core.open(make_opts(MemoryStorage(remote)))
        await peer.compact()
        assert await peer.storage.list_op_actors() == []  # orphan GC'd
        # the author reopens warm (cursor v3) and writes
        del w
        w2 = await Core.open(make_opts(storage, create=False))
        assert w2.opened_from_checkpoint
        await w2.update(lambda s: s.add_ctx(actor, b"fresh"))
        state = w2._data.state
        # dot 4 belongs to the orphan (folded via the peer's snapshot);
        # the new write must have minted dot 5
        assert state.clock.counters[actor] == 5
        assert state.entries[b"orphan"] == {actor: 4}
        assert state.entries[b"fresh"] == {actor: 5}
        cold = await Core.open(make_opts(MemoryStorage(remote)))
        await cold.read_remote()
        await w2.compact()
        await cold.read_remote()
        assert cold.with_state(canonical_bytes) == w2.with_state(
            canonical_bytes
        )

    run(scenario())


def test_vanished_history_refuses_write():
    """The fail-closed half of the guard: a replica with durable
    history facing a view where its merged snapshots vanished and no
    replacement is visible must refuse the write loudly
    (StaleWriterError), not mint possibly-spent dots."""

    class CensoredStorage(MemoryStorage):
        censor = False

        async def list_state_names(self):
            names = await super().list_state_names()
            return [] if self.censor else names

    async def scenario():
        remote = MemoryRemote()
        storage = CensoredStorage(remote)
        w = await Core.open(make_opts(storage))
        await w.update(lambda s: s.add_ctx(w.actor_id, b"a"))
        await w.compact()
        # a peer compacts: w's merged snapshot is GC'd, replaced by the
        # peer's — which the censored listing then hides
        peer = await Core.open(make_opts(MemoryStorage(remote)))
        await peer.update(lambda s: s.add_ctx(peer.actor_id, b"b"))
        await peer.compact()
        del w
        w2 = await Core.open(make_opts(storage, create=False))
        storage.censor = True
        with pytest.raises(StaleWriterError):
            await w2.update(lambda s: s.add_ctx(w2.actor_id, b"c"))
        # the refusal is transient: a repaired view writes normally
        storage.censor = False
        await w2.update(lambda s: s.add_ctx(w2.actor_id, b"c"))
        assert b"c" in w2._data.state.entries

    run(scenario())


# --------------------------------------------------- sim vocabulary


def test_sim_daemon_vocabulary_schedule_roundtrip():
    from crdt_enc_tpu.sim import Schedule, generate
    from crdt_enc_tpu.sim.faults import FaultConfig

    sched = generate(3, 4, 200, FaultConfig.none(), daemon=True)
    kinds = {s.kind for s in sched.steps}
    assert "daemon" in kinds
    assert sched.daemon
    again = Schedule.from_obj(sched.to_obj())
    assert again.daemon and [s.to_obj() for s in again.steps] == [
        s.to_obj() for s in sched.steps
    ]
    # the flag OFF preserves the pre-daemon RNG stream bit-for-bit
    plain = generate(3, 4, 200, FaultConfig.none())
    plain_flagged = generate(3, 4, 200, FaultConfig.none(), daemon=False)
    assert [s.to_obj() for s in plain.steps] == [
        s.to_obj() for s in plain_flagged.steps
    ]
    assert not any(
        s.kind in ("daemon", "ddrain") for s in plain.steps
    )


def test_sim_daemon_schedule_runs_clean():
    """A small no-fault daemon-vocabulary schedule runs a real
    FleetDaemon inside the simulator with zero violations and counted
    daemon cycles."""
    from crdt_enc_tpu.sim import Schedule, Step, run_schedule
    from crdt_enc_tpu.sim.faults import FaultConfig

    sched = Schedule(
        seed=11, n_replicas=3, daemon=True,
        steps=[
            Step("add", 0, 1), Step("add", 1, 2), Step("daemon"),
            Step("add", 2, 3), Step("daemon"), Step("crash", 1),
            Step("daemon"), Step("reopen", 1), Step("daemon"),
            Step("ddrain"), Step("add", 0, 4), Step("daemon"),
        ],
        faults=FaultConfig.none(),
    )
    result = run_schedule(sched)
    assert result.ok, result.violation
    assert result.daemon_cycles == 5

"""The static-analysis engine (crdt_enc_tpu/analysis/).

Per-rule positive (seeded violation caught) and negative (compliant
code passes) fixtures, the pragma/baseline suppression round-trips,
the ``--json`` schema golden, the shim exit codes, the live-repo
tier-1 gate (the whole engine must run clean on this repository inside
its runtime budget), and regression tests for the genuine findings
this PR's rules surfaced and fixed (EXC001 silent native fallbacks in
utils/codec.py + ops/columnar.py, OBS001 unaccounted device_put sites
in parallel/{distributed,mesh,session}.py).

Fixtures are parsed, never executed — a fixture may reference jax or
ctypes freely without importing them.
"""

from __future__ import annotations

import importlib.util
import json
import logging
import pathlib
import textwrap
import time

import numpy as np
import pytest

from crdt_enc_tpu.analysis import Baseline, Project, run, unsuppressed_errors
from crdt_enc_tpu.analysis.baseline import parse_toml
from crdt_enc_tpu.analysis.cli import main as cli_main

REPO = pathlib.Path(__file__).resolve().parent.parent

REGISTRY_DOC = textwrap.dedent(
    """\
    # registry fixture

    ## Span registry

    | name | where |
    |---|---|
    | `phase.x` | fixture |
    | `stream.h2d` | fixture |

    ## Counter & gauge registry

    | name | where |
    |---|---|
    | `h2d_bytes` | fixture |
    | `events_dropped` | obs-internal |
    """
)


def analyze(tmp_path, src, rules, *, rel="crdt_enc_tpu/fixture.py",
            registry=True, baseline_text=None):
    """Write a one-file fixture project and run the selected rules."""
    path = tmp_path / rel
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(textwrap.dedent(src))
    doc = tmp_path / "docs" / "observability.md"
    doc.parent.mkdir(exist_ok=True)
    if registry:
        doc.write_text(REGISTRY_DOC)
    baseline = None
    if baseline_text is not None:
        bp = tmp_path / "tools" / "analysis_baseline.toml"
        bp.parent.mkdir(exist_ok=True)
        bp.write_text(textwrap.dedent(baseline_text))
        baseline = Baseline.load(bp)
    # scan (not explicit paths): fixtures must exercise the FULL run
    # semantics, including project-global checks a partial run skips
    project = Project(tmp_path)
    return run(project, rules, baseline), baseline


def errors_of(findings):
    return unsuppressed_errors(findings)


# ------------------------------------------------------------------ FFI001


def test_ffi_partial_binding_caught(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        import ctypes
        u8p = ctypes.POINTER(ctypes.c_uint8)
        def _bind(lib):
            lib.half_bound.argtypes = [u8p, ctypes.c_uint64]
        """,
        ["FFI001"],
    )
    msgs = [f.message for f in errors_of(findings)]
    assert any("half_bound" in m and "not restype" in m for m in msgs)


def test_ffi_pointer_without_capacity_caught(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        import ctypes
        u8p = ctypes.POINTER(ctypes.c_uint8)
        def _bind(lib):
            lib.unbounded_fill.argtypes = [u8p, u8p]
            lib.unbounded_fill.restype = None
        """,
        ["FFI001"],
    )
    assert any(
        "capacity" in f.message and "unbounded_fill" in f.message
        for f in errors_of(findings)
    )


def test_ffi_discarded_status_and_undeclared_call_caught(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        import ctypes
        from . import native
        u8p = ctypes.POINTER(ctypes.c_uint8)
        def _bind(lib):
            lib.checked_fn.argtypes = [u8p, ctypes.c_uint64]
            lib.checked_fn.restype = ctypes.c_int64
        def use():
            lib = native.load()
            lib.checked_fn(None, 0)      # status discarded
            lib.never_declared(None)     # undeclared foreign call
        """,
        ["FFI001"],
    )
    msgs = [f.message for f in errors_of(findings)]
    assert any("discarded" in m for m in msgs)
    assert any("never_declared" in m and "undeclared" in m for m in msgs)


def test_ffi_clean_binding_passes(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        import ctypes
        from . import native
        u8p = ctypes.POINTER(ctypes.c_uint8)
        def _bind(lib):
            lib.good_fn.argtypes = [u8p, ctypes.c_uint64]
            lib.good_fn.restype = ctypes.c_int64
        def use():
            lib = native.load()
            rc = lib.good_fn(None, 0)
            if rc != 0:
                raise RuntimeError("native failure")
        """,
        ["FFI001"],
    )
    assert errors_of(findings) == []


def test_ffi_loop_getattr_binding_resolved(tmp_path):
    # the _bind loop form: for name in (...): fn = getattr(lib, name)
    findings, _ = analyze(
        tmp_path,
        """
        import ctypes
        u8p = ctypes.POINTER(ctypes.c_uint8)
        def _bind(lib):
            for name in ("enc_a", "enc_b"):
                fn = getattr(lib, name)
                fn.argtypes = [u8p, ctypes.c_uint64]
                fn.restype = None
        """,
        ["FFI001"],
    )
    assert errors_of(findings) == []


# ------------------------------------------------------------------ JIT001


def test_jit_traced_branch_caught(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        import jax
        @jax.jit
        def f(x, y):
            if x > 0:
                return y
            return -y
        """,
        ["JIT001"],
    )
    errs = errors_of(findings)
    assert len(errs) == 1 and "`x`" in errs[0].message


def test_jit_static_and_shape_branches_pass(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        import jax
        from functools import partial
        @partial(jax.jit, static_argnames=("mode",))
        def f(x, mode, y=None):
            if mode == "fast":
                x = x * 2
            if y is None:
                y = x
            if x.shape[0] > 8:
                y = y + 1
            if len(x) > 4:
                y = y - 1
            while y.ndim > 2:
                y = y.sum(0)
            return x + y
        """,
        ["JIT001"],
    )
    assert errors_of(findings) == []


# ------------------------------------------------------------------ JIT002


def test_jit_static_value_derived_caught(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        import jax
        from functools import partial
        @partial(jax.jit, static_argnames=("num_values",))
        def fold(col, num_values):
            return col
        def caller(col):
            return fold(col, num_values=int(col.max()) + 1)
        """,
        ["JIT002"],
    )
    errs = errors_of(findings)
    assert len(errs) == 1 and "num_values" in errs[0].message


def test_jit_direct_call_decorator_form_resolved(tmp_path):
    """`@jax.jit(static_argnums=...)` (no functools.partial) must be
    recognized — both rules would otherwise skip the function."""
    findings, _ = analyze(
        tmp_path,
        """
        import jax
        @jax.jit(static_argnums=(1,))
        def fold(col, n):
            if col > 0:
                return col
            return -col
        def caller(col):
            return fold(col, int(col.max()))
        """,
        ["JIT001", "JIT002"],
    )
    rules_hit = {f.rule for f in errors_of(findings)}
    assert rules_hit == {"JIT001", "JIT002"}


def test_jit_static_quantized_and_literal_pass(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        import jax
        from functools import partial

        def _bucket(n, floor=8):
            return max(floor, 1 << (n - 1).bit_length())

        @partial(jax.jit, static_argnames=("num_members", "num_replicas"))
        def fold(col, num_members, num_replicas):
            return col

        def caller(col, R):
            E = _bucket(len(col))
            fold(col, E, num_replicas=R)   # R: param pass-through
            return fold(col, 128, num_replicas=col.shape[1])
        """,
        ["JIT002"],
    )
    assert errors_of(findings) == []


def test_jit_static_forwarded_through_wrapper_caught(tmp_path):
    """A non-jitted wrapper forwarding its param into a jitted static
    becomes a checked target itself: the raw value is flagged at the
    OUTER call site, not laundered through one level of indirection."""
    findings, _ = analyze(
        tmp_path,
        """
        import jax
        from functools import partial

        def _bucket(n, floor=8):
            return max(floor, 1 << (n - 1).bit_length())

        @partial(jax.jit, static_argnames=("cap",))
        def fold(col, cap):
            return col

        def helper(col, n):
            return fold(col, cap=n)

        def bad(col):
            return helper(col, int(col.max()))

        def good(col):
            return helper(col, _bucket(len(col)))
        """,
        ["JIT002"],
    )
    errs = errors_of(findings)
    assert len(errs) == 1
    assert "`helper`" in errs[0].message and "flows into" in errs[0].message


def test_jit_static_instance_attr_provenance(tmp_path):
    """`self.X` statics are bounded iff every in-class assignment is —
    a raw `col.max()` stashed on the instance is the same recompile
    bug one hop later; quantized/constant attrs and self-referential
    rebinds (`self.E = round_up(self.E)`) stay clean."""
    findings, _ = analyze(
        tmp_path,
        """
        import jax
        from functools import partial

        def _bucket(n, floor=8):
            return max(floor, 1 << (n - 1).bit_length())

        @partial(jax.jit, static_argnames=("cap",))
        def fold(col, cap):
            return col

        class Bad:
            def __init__(self, col):
                self.raw_max = int(col.max())
            def go(self, col):
                return fold(col, cap=self.raw_max)

        class Good:
            def __init__(self, col, mp):
                self.cap = _bucket(len(col))
                self.cap = -(-self.cap // mp) * mp
                self.lim = 128
            def go(self, col):
                return fold(col, cap=self.cap) + fold(col, cap=self.lim)
        """,
        ["JIT002"],
    )
    errs = errors_of(findings)
    assert len(errs) == 1 and "`cap`" in errs[0].message
    assert errs[0].context == "Bad.go"


def test_jit_cross_module_name_collision_not_flagged(tmp_path):
    """Bare-name callee keying must not reach across modules onto an
    unrelated plain function: module b's own `def fold(items, label)`
    shadows module a's jitted `fold` for b's unqualified calls."""
    (tmp_path / "crdt_enc_tpu").mkdir(parents=True)
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text(REGISTRY_DOC)
    (tmp_path / "crdt_enc_tpu" / "a.py").write_text(textwrap.dedent(
        """
        import jax
        from functools import partial
        @partial(jax.jit, static_argnames=("n",))
        def fold(col, n):
            return col
        """
    ))
    (tmp_path / "crdt_enc_tpu" / "b.py").write_text(textwrap.dedent(
        """
        def fold(items, label):
            return [label + i for i in items]
        def use(data, tag):
            return fold(data, tag.upper())
        """
    ))
    findings = run(Project(tmp_path), ["JIT002"], None)
    assert errors_of(findings) == []


def test_jit_same_named_wrappers_keep_own_param_orders(tmp_path):
    """Forwarding entries are keyed per owner: module b's 3-param `fold`
    wrapper must not inherit module a's 2-param order (which would
    mis-map positional args into the wrong static slot)."""
    (tmp_path / "crdt_enc_tpu").mkdir(parents=True)
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text(REGISTRY_DOC)
    (tmp_path / "crdt_enc_tpu" / "a.py").write_text(textwrap.dedent(
        """
        import jax
        from functools import partial
        @partial(jax.jit, static_argnames=("cap",))
        def jfold(col, cap):
            return col
        def fold(x, cap):
            return jfold(x, cap=cap)
        def use_a(col):
            return fold(col, 64)
        """
    ))
    (tmp_path / "crdt_enc_tpu" / "b.py").write_text(textwrap.dedent(
        """
        import jax
        from functools import partial
        @partial(jax.jit, static_argnames=("n",))
        def jfold2(col, n):
            return col
        def fold(a, b, c):
            return jfold2(a, n=c)
        def benign(col):
            return fold(col, int(col.max()), 8)   # unbounded arg is NOT forwarded
        def guilty(col):
            return fold(col, 1, int(col.max()))   # position 2 IS forwarded
        """
    ))
    findings = run(Project(tmp_path), ["JIT002"], None)
    errs = errors_of(findings)
    assert len(errs) == 1
    assert errs[0].context == "guilty" and "`c`" in errs[0].message


def test_jit_same_named_jitted_defs_resolve_locally(tmp_path):
    """The jitted-callee map is keyed per definition: a module's call to
    its OWN jitted `fold` is checked against that signature, and a bare
    call in a third module that could mean either of two same-named
    jitted defs is skipped rather than checked against a guessed (or
    merged) signature."""
    (tmp_path / "crdt_enc_tpu").mkdir(parents=True)
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text(REGISTRY_DOC)
    (tmp_path / "crdt_enc_tpu" / "a.py").write_text(textwrap.dedent(
        """
        import jax
        from functools import partial
        @partial(jax.jit, static_argnames=("n",))
        def fold(col, n):
            return col
        def use_a(col):
            return fold(col, int(col.max()))
        """
    ))
    (tmp_path / "crdt_enc_tpu" / "b.py").write_text(textwrap.dedent(
        """
        import jax
        from functools import partial
        @partial(jax.jit, static_argnames=("mode",))
        def fold(data, mode):
            return data
        """
    ))
    (tmp_path / "crdt_enc_tpu" / "c.py").write_text(textwrap.dedent(
        """
        def use_c(col):
            return fold(col, int(col.max()))   # ambiguous: a's or b's?
        """
    ))
    findings = run(Project(tmp_path), ["JIT002"], None)
    errs = errors_of(findings)
    assert len(errs) == 1
    assert errs[0].path == "crdt_enc_tpu/a.py" and errs[0].context == "use_a"


def test_jit_static_self_referential_local_rebind_passes(tmp_path):
    """`E = -(-E // mp) * mp` after a quantized init (the session.py
    _grow_device_planes shape) must not be flagged: the rebind cycle
    adds no unboundedness — the engine once mistook it for one via the
    recursion depth guard."""
    findings, _ = analyze(
        tmp_path,
        """
        import jax
        from functools import partial

        def _bucket(n, floor=8):
            return max(floor, 1 << (n - 1).bit_length())

        @partial(jax.jit, static_argnames=("cap",))
        def fold(col, cap):
            return col

        def caller(col, mp):
            E = _bucket(len(col))
            E = -(-E // mp) * mp
            return fold(col, cap=E)
        """,
        ["JIT002"],
    )
    assert errors_of(findings) == []


def test_jit_star_unpacked_positions_not_guessed(tmp_path):
    """`fold(*planes, x)` binds x to a position only len(planes) knows —
    mapping by index would check the wrong parameter name (flagging a
    bounded call, or admitting the real static).  Positions past the
    Starred node are skipped; keyword-bound statics are still checked."""
    findings, _ = analyze(
        tmp_path,
        """
        import jax
        from functools import partial
        @partial(jax.jit, static_argnames=("n",))
        def fold(a, n):
            return a
        def caller(col, planes):
            fold(*planes, int(col.max()))
            return fold(*planes, n=int(col.max()))
        """,
        ["JIT002"],
    )
    errs = errors_of(findings)
    assert len(errs) == 1 and "n" in errs[0].message


# ------------------------------------------------------------------ EXC001


def test_exc_silent_native_fallback_caught(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        from .. import native
        def fast(buf):
            try:
                lib = native.load()
                return lib.decode(buf)
            except Exception:
                return None
        """,
        ["EXC001"],
    )
    errs = errors_of(findings)
    assert len(errs) == 1 and "silently disable" in errs[0].message


def test_exc_logged_or_reraising_fallback_passes(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        import logging
        from .. import native
        logger = logging.getLogger(__name__)

        def _warn_no_native(e):
            logger.warning("native unavailable: %r", e)

        def fast(buf):
            try:
                lib = native.load()
                return lib.decode(buf)
            except Exception as e:
                _warn_no_native(e)
                return None

        def strict(buf):
            try:
                return native.load().decode(buf)
            except Exception as e:
                raise RuntimeError("decode failed") from e

        def unrelated(buf):
            try:
                return int(buf)
            except Exception:
                return None   # no native fast path in the try body
        """,
        ["EXC001"],
    )
    assert errors_of(findings) == []


# ------------------------------------------------------------------ THR001


def test_thread_discipline_caught_and_baseline_pinned(tmp_path):
    src = """
        import threading
        def spawn():
            t1 = threading.Thread(target=print)
            t2 = threading.Thread(target=print)
            return t1, t2
    """
    findings, _ = analyze(tmp_path, src, ["THR001"])
    assert len(errors_of(findings)) == 2

    # a max=1 baseline pin absorbs ONE site; the second still surfaces
    findings, baseline = analyze(
        tmp_path, src, ["THR001"],
        baseline_text="""
        [[suppress]]
        rule = "THR001"
        path = "crdt_enc_tpu/fixture.py"
        context = "spawn"
        reason = "fixture: one sanctioned site"
        max = 1
        """,
    )
    assert len(errors_of(findings)) == 1
    assert baseline.stale_entries() == []


def test_thread_from_import_alias_caught(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        from threading import Thread
        def spawn():
            return Thread(target=print)
        """,
        ["THR001"],
    )
    assert len(errors_of(findings)) == 1


def test_thread_module_alias_caught(tmp_path):
    """`import threading as thr; thr.Thread(...)` must not bypass the
    discipline — module aliasing once escaped the rule entirely."""
    findings, _ = analyze(
        tmp_path,
        """
        import threading as thr
        def spawn():
            return thr.Thread(target=print)
        """,
        ["THR001"],
    )
    assert len(errors_of(findings)) == 1


# ------------------------------------------------------------------ SPN001


def test_span_unregistered_name_caught(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        from .utils import trace
        def work():
            with trace.span("phase.x"):
                trace.add("not.in.registry", 1)
            with trace.span("stream.h2d"):
                trace.add("h2d_bytes", 1)
        """,
        ["SPN001"],
    )
    errs = errors_of(findings)
    assert len(errs) == 1 and "not.in.registry" in errs[0].message


def test_span_stale_stream_proof_is_error(tmp_path):
    # registry registers stream.h2d but the fixture never emits it
    findings, _ = analyze(
        tmp_path,
        """
        from .utils import trace
        def work():
            trace.add("h2d_bytes", 4)
            with trace.span("phase.x"):
                pass
        """,
        ["SPN001"],
    )
    errs = errors_of(findings)
    assert len(errs) == 1
    assert "stream.h2d" in errs[0].message and errs[0].path.endswith(
        "observability.md"
    )


def test_span_fstring_name_is_warning_not_error(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        from .utils import trace
        def work(k):
            trace.add("h2d_bytes", 1)
            with trace.span("phase.x"):
                trace.add(f"chunk.{k}", 1)
            with trace.span("stream.h2d"):
                pass
        """,
        ["SPN001"],
    )
    assert errors_of(findings) == []
    warns = [f for f in findings if f.severity == "warning"]
    assert any("f-string" in f.message for f in warns)


def test_span_qualified_receiver_spelling_matched(tmp_path):
    """The qualified spelling `obs.record.add(...)` hits the same
    matcher as `trace.add(...)` — the old regex lint matched both, and
    SEC001 shares this matcher for its trace-meta sink."""
    findings, _ = analyze(
        tmp_path,
        """
        from . import obs
        def work():
            obs.record.add("not.in.registry", 1)
            obs.record.add("h2d_bytes", 1)
            with obs.record.span("phase.x"):
                pass
            with obs.record.span("stream.h2d"):
                pass
        """,
        ["SPN001"],
    )
    errs = errors_of(findings)
    assert len(errs) == 1 and "not.in.registry" in errs[0].message


# ------------------------------------------------------------------ OBS001


def test_obs_unaccounted_device_put_caught(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        import jax
        def upload(x):
            return jax.device_put(x)
        """,
        ["OBS001"],
    )
    assert len(errors_of(findings)) == 1


def test_obs_accounted_device_put_passes(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        import jax
        from .utils import trace
        def upload(x):
            trace.add("h2d_bytes", x.nbytes)
            return jax.device_put(x)
        """,
        ["OBS001"],
    )
    assert errors_of(findings) == []


def test_obs_multihost_placement_needs_accounting_too(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        import jax
        def upload(sharding, x):
            return jax.make_array_from_process_local_data(sharding, x)
        """,
        ["OBS001"],
    )
    assert len(errors_of(findings)) == 1


def test_obs_module_level_put_needs_module_level_accounting(tmp_path):
    """Accounting inside an unrelated function must not excuse a
    module-level transfer; module-level accounting does."""
    findings, _ = analyze(
        tmp_path,
        """
        import jax
        import numpy as np
        from .utils import trace
        _ZERO = jax.device_put(np.zeros(4))
        def unrelated():
            trace.add("h2d_bytes", 0)
        """,
        ["OBS001"],
    )
    assert len(errors_of(findings)) == 1

    findings, _ = analyze(
        tmp_path,
        """
        import jax
        import numpy as np
        from .utils import trace
        trace.add("h2d_bytes", 16)
        _ZERO = jax.device_put(np.zeros(4))
        """,
        ["OBS001"],
    )
    assert errors_of(findings) == []


def test_obs_unaccounted_jnp_asarray_caught(tmp_path):
    """`jnp.asarray` on host data IS an upload (the ISSUE's
    'jnp.asarray-to-device' half of the invariant); `np.asarray` never
    leaves the host and must not be flagged."""
    findings, _ = analyze(
        tmp_path,
        """
        import jax.numpy as jnp
        import numpy as np
        def to_device(x):
            return jnp.asarray(x)
        def host_only(x):
            return np.asarray(x)
        """,
        ["OBS001"],
    )
    errs = errors_of(findings)
    assert len(errs) == 1 and errs[0].context == "to_device"


def test_obs_asarray_inside_jit_exempt(tmp_path):
    """Inside a jit body `jnp.asarray` is a traced no-op, not a runtime
    transfer — the pallas_merge kernel shape."""
    findings, _ = analyze(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp
        from functools import partial
        @partial(jax.jit, static_argnames=("interpret",))
        def kernel(xs, interpret=False):
            xs = jnp.asarray(xs, jnp.int32)
            return xs
        """,
        ["OBS001"],
    )
    assert errors_of(findings) == []


def test_obs_asarray_in_closure_inside_jit_exempt(tmp_path):
    """A def nested in a jit body (scan/cond body shape) is traced too —
    its jnp.asarray is a no-op; the jit decorator must be found on the
    OUTER function, not just the innermost enclosing def."""
    findings, _ = analyze(
        tmp_path,
        """
        import jax
        import jax.numpy as jnp
        @jax.jit
        def fold(xs):
            def body(carry, x):
                return carry + jnp.asarray(x), None
            return jax.lax.scan(body, jnp.zeros(()), xs)
        """,
        ["OBS001"],
    )
    assert errors_of(findings) == []


def test_obs_scope_excludes_benchmarks(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        import jax
        def upload(x):
            return jax.device_put(x)
        """,
        ["OBS001"],
        rel="benchmarks/fixture.py",
    )
    assert errors_of(findings) == []


# ------------------------------------------------------------------ SEC001


def test_sec_key_in_log_and_exception_caught(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        import logging
        logger = logging.getLogger(__name__)
        def unwrap(key, blob):
            logger.warning("unwrap failed for key %r", key)
            material = bytes(key)
            raise ValueError(f"bad key material: {material}")
        """,
        ["SEC001"],
    )
    errs = errors_of(findings)
    assert len(errs) == 2
    assert any("log call" in f.message for f in errs)
    assert any("exception message" in f.message for f in errs)


def test_sec_public_facts_about_secrets_pass(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        import logging
        logger = logging.getLogger(__name__)
        def unwrap(key, blob):
            if len(key) != 32:
                raise ValueError(f"invalid key length {len(key)}")
            logger.info("unwrapping with key_id %s", key.key_id)
            rc = decrypt(key, blob)          # status code: taint blocked
            logger.debug("decrypt rc=%d", rc)
            return rc
        """,
        ["SEC001"],
    )
    assert errors_of(findings) == []


def test_sec_taint_in_trace_meta_caught(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        from .utils import trace
        def seal(passphrase, data):
            with trace.span("phase.x", meta=passphrase):
                return data
        """,
        ["SEC001"],
    )
    assert len(errors_of(findings)) == 1


def test_sec_nonassign_binding_forms_are_sources(tmp_path):
    """Secrets bound via for targets, annotated assignment, or with-as
    must taint like a plain assignment — each of these once escaped the
    rule entirely."""
    findings, _ = analyze(
        tmp_path,
        """
        import logging
        logger = logging.getLogger(__name__)
        def rotate(ring, lockbox, storage):
            for key in ring:
                logger.warning("rotating %r", key)
            passphrase: bytes = storage.load()
            logger.warning("loaded %r", passphrase)
            with lockbox.open() as key_material:
                logger.warning("opened %r", key_material)
        """,
        ["SEC001"],
    )
    errs = errors_of(findings)
    assert len(errs) == 3
    hit = " ".join(f.message for f in errs)
    assert "key" in hit and "passphrase" in hit and "key_material" in hit


def test_sec_loop_carried_taint_reaches_fixpoint(tmp_path):
    """A taint chain assembled against source order (`out = buf` textually
    BEFORE `buf = bytes(key_material)`, loop-carried) still converges —
    a single source-order pass would miss it.  A value derived through a
    non-identity call (`checksum(...)`) stays clean."""
    findings, _ = analyze(
        tmp_path,
        """
        import logging
        logger = logging.getLogger(__name__)
        def drain(key_material, chunks):
            out = b""
            for c in chunks:
                out = buf
                buf = bytes(key_material)
            logger.warning("drained %r", out)
            rc = checksum(key_material)
            logger.debug("checksum rc=%d", rc)
        """,
        ["SEC001"],
    )
    errs = errors_of(findings)
    assert len(errs) == 1
    assert "`out`" in errs[0].message and "log call" in errs[0].message


# ----------------------------------------------------- pragma suppression


def test_pragma_same_line_and_line_above_roundtrip(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        import threading
        def spawn():
            t = threading.Thread(target=print)  # lint: disable=THR001
            # lint: disable=THR001
            u = threading.Thread(target=print)
            return t, u
        """,
        ["THR001"],
    )
    assert errors_of(findings) == []
    assert [f.suppressed for f in findings] == ["pragma", "pragma"]


def test_pragma_wrong_rule_does_not_suppress(tmp_path):
    findings, _ = analyze(
        tmp_path,
        """
        import threading
        def spawn():
            return threading.Thread(target=print)  # lint: disable=OBS001
        """,
        ["THR001"],
    )
    assert len(errors_of(findings)) == 1


# ----------------------------------------------------------- baseline file


def test_baseline_contains_and_stale_detection(tmp_path):
    src = """
        import threading
        def spawn():
            return threading.Thread(target=print)
    """
    findings, baseline = analyze(
        tmp_path, src, ["THR001"],
        baseline_text="""
        [[suppress]]
        rule = "THR001"
        path = "crdt_enc_tpu/fixture.py"
        contains = "bare threading.Thread"
        reason = "fixture"

        [[suppress]]
        rule = "THR001"
        path = "crdt_enc_tpu/gone.py"
        reason = "this file no longer exists"
        """,
    )
    assert errors_of(findings) == []
    stale = baseline.stale_entries()
    assert len(stale) == 1 and stale[0].path == "crdt_enc_tpu/gone.py"


def test_baseline_toml_subset_rejects_garbage():
    with pytest.raises(ValueError):
        parse_toml("[[suppress]]\nrule = [1, 2]\n")
    with pytest.raises(ValueError):
        parse_toml("[badtable]\n")
    entries = parse_toml(
        '# comment\n[[suppress]]\nrule = "X"\nmax = 2\n'
    )
    assert entries == [{"rule": "X", "max": 2}]


def test_baseline_hash_inside_quoted_reason_survives():
    entries = parse_toml(
        '[[suppress]]\nrule = "X"\nreason = "see issue #5"  # trailing\n'
    )
    assert entries == [{"rule": "X", "reason": "see issue #5"}]


def test_baseline_unknown_key_rejected(tmp_path):
    """A typo'd narrowing key (`contain` for `contains`) must error, not
    silently widen the suppression to the whole file."""
    bp = tmp_path / "b.toml"
    bp.write_text(
        '[[suppress]]\nrule = "X"\npath = "a.py"\nreason = "r"\n'
        'contain = "oops"\n'
    )
    with pytest.raises(ValueError, match="unknown key"):
        Baseline.load(bp)


# ------------------------------------------------------------- CLI surface


def test_cli_json_schema_golden(tmp_path, capsys):
    (tmp_path / "crdt_enc_tpu").mkdir()
    (tmp_path / "crdt_enc_tpu" / "mod.py").write_text(
        "import threading\n"
        "def spawn():\n"
        "    return threading.Thread(target=print)\n"
    )
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text(REGISTRY_DOC)
    rc = cli_main(["--json", "--rule", "THR001", "--root", str(tmp_path)])
    out = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert set(out) == {
        "version", "root", "elapsed_s", "rules", "findings",
        "stale_baseline", "summary",
    }
    assert out["version"] == 2 and out["rules"] == ["THR001"]
    (finding,) = out["findings"]
    # v2: findings carry `chain` (provenance call path; None for
    # single-site rules like THR001)
    assert set(finding) == {
        "rule", "severity", "path", "line", "message", "context",
        "suppressed", "chain",
    }
    assert finding["rule"] == "THR001" and finding["suppressed"] is None
    assert set(out["summary"]) == {
        "files", "errors", "warnings", "suppressed",
    }


def test_cli_unknown_rule_is_usage_error(capsys):
    assert cli_main(["--rule", "NOPE999", "--root", str(REPO)]) == 2


def test_cli_path_subset_skips_project_global_checks(capsys):
    """A single-file run must not report stream.* proof spans as
    unemitted or unrelated baseline entries as stale (they are judged
    against the whole tree, which a path subset doesn't see)."""
    rc = cli_main(
        ["--diff-baseline", "--root", str(REPO),
         str(REPO / "crdt_enc_tpu" / "utils" / "codec.py")]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "STALE" not in out and "stream." not in out


def test_cli_path_subset_skips_cross_file_ffi_declarations(capsys):
    """ops/ calls native handles whose argtypes/restype declarations
    live in native/load.py — a path-subset run that can't see the
    declaring module must not report them as undeclared foreign calls
    (same partial-run contract as the stale-span and stale-baseline
    skips).  The full scan still judges them."""
    rc = cli_main(
        ["--root", str(REPO),
         str(REPO / "crdt_enc_tpu" / "ops" / "native_decode.py")]
    )
    out = capsys.readouterr().out
    assert rc == 0
    assert "undeclared foreign call" not in out


def test_cli_out_of_scope_paths_skipped_not_linted(capsys):
    """Explicit paths under exempt trees (tests/ seeds violations on
    purpose) are skipped with a note — a hook feeding changed files must
    not get spurious library-rule errors or a failing exit code."""
    rc = cli_main(
        ["--root", str(REPO), str(REPO / "tests" / "test_obs.py")]
    )
    captured = capsys.readouterr()
    assert rc == 0
    assert "outside the analysis scope" in captured.err
    assert "0 error(s)" in captured.out

    # mixed list: the in-scope file is still analyzed
    rc = cli_main(
        ["--root", str(REPO),
         str(REPO / "tests" / "test_obs.py"),
         str(REPO / "crdt_enc_tpu" / "utils" / "codec.py")]
    )
    captured = capsys.readouterr()
    assert rc == 0
    assert "1 files" in captured.out


def test_cli_directory_arg_expands_to_in_scope_files(tmp_path, capsys):
    """A directory argument means "every in-scope file under it" — it
    must not be classified out-of-scope (no .py suffix) and produce a
    false-clean exit 0 with zero files analyzed."""
    pkg = tmp_path / "crdt_enc_tpu" / "ops"
    pkg.mkdir(parents=True)
    (pkg / "bad.py").write_text(
        textwrap.dedent(
            """
            import threading
            def f():
                threading.Thread(target=print).start()
            """
        )
    )
    (tmp_path / "docs").mkdir()
    rc = cli_main(
        ["--root", str(tmp_path), "--no-baseline", "--rule", "THR001",
         str(tmp_path / "crdt_enc_tpu")]
    )
    captured = capsys.readouterr()
    assert rc == 1
    assert "THR001" in captured.out and "1 files" in captured.out
    assert "outside the analysis scope" not in captured.err

    # a directory wholly outside the scan scope still skips with a note
    tests_dir = tmp_path / "tests"
    tests_dir.mkdir()
    (tests_dir / "test_x.py").write_text("x = 1\n")
    rc = cli_main(
        ["--root", str(tmp_path), "--no-baseline", "--rule", "THR001",
         str(tests_dir)]
    )
    captured = capsys.readouterr()
    assert rc == 0
    assert "contains no in-scope files" in captured.err
    assert "0 files" in captured.out


def test_engine_non_utf8_file_degrades_to_finding(tmp_path):
    """One undecodable file becomes an ENG000 finding; every other file
    is still analyzed (the run must not abort with exit 2)."""
    (tmp_path / "crdt_enc_tpu").mkdir(parents=True)
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text(REGISTRY_DOC)
    (tmp_path / "crdt_enc_tpu" / "ok.py").write_text("x = 1\n")
    (tmp_path / "crdt_enc_tpu" / "bad.py").write_bytes(b"# caf\xe9\nx = 1\n")
    project = Project(tmp_path)
    findings = run(project, ["THR001"], None)
    eng = [f for f in findings if f.rule == "ENG000"]
    assert len(eng) == 1 and "UTF-8" in eng[0].message
    assert any(m.rel == "crdt_enc_tpu/ok.py" for m in project.modules)


def test_cli_bad_paths_are_usage_errors(tmp_path, capsys):
    assert cli_main(["--root", str(REPO), "/tmp/does-not-exist-xyz.py"]) == 2
    outside = tmp_path / "outside.py"
    outside.write_text("x = 1\n")
    assert cli_main(["--root", str(REPO), str(outside)]) == 2


def test_cli_non_checkout_root_is_usage_error(tmp_path, capsys):
    """An installed `crdt-analyze` (site-packages root) must say 'pass
    --root', not limp into bogus findings."""
    assert cli_main(["--root", str(tmp_path)]) == 2
    assert "--root" in capsys.readouterr().err


def test_cli_list_rules_names_all_twelve(capsys):
    assert cli_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rule_id in (
        "FFI001", "JIT001", "JIT002", "EXC001", "THR001", "SPN001",
        "OBS001", "SEC001", "ASY001", "DET001", "MUT001", "LCK001",
    ):
        assert rule_id in out


# ------------------------------------------------- live repo: tier-1 gate


def test_live_repo_analysis_clean_within_budget():
    """The tier-1 gate (replaces the old per-script hooks in
    tests/test_obs.py): the whole engine runs clean against the
    committed baseline — no unsuppressed errors, no stale entries —
    inside the 10s budget on this 2-core box."""
    t0 = time.monotonic()
    rc = cli_main(["--diff-baseline", "--root", str(REPO)])
    elapsed = time.monotonic() - t0
    assert rc == 0
    assert elapsed < 10.0, f"analyzer took {elapsed:.1f}s (budget 10s)"


def _load_tool(name: str):
    spec = importlib.util.spec_from_file_location(
        name, REPO / "tools" / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_span_shim_exit_code():
    """tools/check_span_names.py kept its CLI contract (exit 0 clean)."""
    assert _load_tool("check_span_names").main([]) == 0


def test_thread_shim_exit_code():
    """tools/check_thread_discipline.py kept its CLI contract."""
    assert _load_tool("check_thread_discipline").main([]) == 0


# ------------------------------------- regressions for the genuine fixes


def test_codec_native_fallback_warns_once(monkeypatch, caplog):
    """EXC001 fix: losing the native canon_pack logs exactly one warning
    and the Python path still produces canonical bytes."""
    import msgpack

    from crdt_enc_tpu import native
    from crdt_enc_tpu.utils import codec

    monkeypatch.setattr(codec, "_native_pack", None)
    monkeypatch.setattr(
        native, "load_state",
        lambda: (_ for _ in ()).throw(RuntimeError("no build")),
    )
    obj = {b"b": 1, b"a": [2, 3]}
    with caplog.at_level(logging.WARNING, logger="crdt_enc_tpu.codec"):
        out1 = codec.pack(obj)
        out2 = codec.pack(obj)
    warns = [
        r for r in caplog.records if "canon_pack unavailable" in r.message
    ]
    assert len(warns) == 1  # once per process, not per call
    assert out1 == out2
    assert codec.unpack(out1) == msgpack.unpackb(
        out1, raw=False, use_list=False, strict_map_key=False
    )


def test_columnar_native_fallback_warns_once(monkeypatch, caplog):
    """EXC001 fix: the state-assembly fast path failing logs once and
    the caller falls through to the Python path (None sentinel)."""
    from crdt_enc_tpu import native
    from crdt_enc_tpu.ops import columnar

    monkeypatch.setattr(columnar, "_warned_no_native_state", False)
    monkeypatch.setattr(
        native, "load_state",
        lambda: (_ for _ in ()).throw(RuntimeError("no build")),
    )
    empty = np.array([], np.int64)
    with caplog.at_level(logging.WARNING, logger="crdt_enc_tpu.columnar"):
        r1 = columnar._orset_fresh_fold_native(
            None, empty, empty, empty, empty, [], [], empty
        )
        r2 = columnar._orset_fresh_fold_native(
            None, empty, empty, empty, empty, [], [], empty
        )
    assert r1 is None and r2 is None
    warns = [
        r for r in caplog.records
        if "state assembly unavailable" in r.message
    ]
    assert len(warns) == 1


@pytest.mark.parametrize("shape", [(2, 1)])
def test_replicate_and_global_op_batch_account_h2d(shape):
    """OBS001 fix: the distributed placement helpers count their
    transfers at issue."""
    jax = pytest.importorskip("jax")
    from crdt_enc_tpu.parallel import global_op_batch, make_mesh, replicate
    from crdt_enc_tpu.utils import trace

    mesh = make_mesh(shape)
    trace.reset()
    arr = np.arange(64, dtype=np.int32)
    replicate(mesh, arr)
    assert trace.snapshot()["counters"]["h2d_bytes"] == arr.nbytes

    trace.reset()
    kind = np.zeros(8, np.int8)
    member = np.zeros(8, np.int32)
    actor = np.zeros(8, np.int32)
    counter = np.ones(8, np.int32)
    global_op_batch(mesh, kind, member, actor, counter, num_replicas=2)
    # padded to a dp multiple: at least the raw column bytes
    assert trace.snapshot()["counters"]["h2d_bytes"] >= (
        kind.nbytes + member.nbytes + actor.nbytes + counter.nbytes
    )
    trace.reset()


def test_sharded_stream_planes_account_h2d():
    """OBS001 fix: zero-seeded sharded planes count their upload inside
    the helper (the session caller no longer double-counts)."""
    pytest.importorskip("jax")
    from crdt_enc_tpu.parallel import mesh as pmesh
    from crdt_enc_tpu.utils import trace

    m = pmesh.make_mesh((1, 2))
    trace.reset()
    E_pad, R = 8, 2
    clock, add, rm = pmesh.sharded_stream_planes(m, E_pad, R)
    expected = 4 * (max(R, 1) + 2 * E_pad * R)
    assert trace.snapshot()["counters"]["h2d_bytes"] == expected
    assert add.shape == (E_pad, R)
    trace.reset()


def test_orset_merge_many_accounts_host_upload():
    """OBS001 fix: the merge front door's `jnp.asarray` coercion counts
    host-resident stacks at issue; already-device inputs add nothing."""
    pytest.importorskip("jax")
    import jax.numpy as jnp

    from crdt_enc_tpu import ops as K
    from crdt_enc_tpu.utils import trace

    S, E, R = 3, 4, 2
    clocks = np.ones((S, R), np.int32)
    adds = np.ones((S, E, R), np.int32)
    rms = np.zeros((S, E, R), np.int32)

    trace.reset()
    K.orset_merge_many(clocks, adds, rms, impl="tree")
    expected = clocks.nbytes + adds.nbytes + rms.nbytes
    assert trace.snapshot()["counters"]["h2d_bytes"] == expected

    trace.reset()
    K.orset_merge_many(
        jnp.asarray(clocks), jnp.asarray(adds), jnp.asarray(rms), impl="tree"
    )
    assert trace.snapshot()["counters"].get("h2d_bytes", 0) == 0
    trace.reset()

"""The examples/ app as a smoke test — the reference uses its example the
same way (SURVEY.md §4: example-as-smoke-test), but with assertions added."""

import asyncio
import importlib.util
import sys
from pathlib import Path

EXAMPLE = Path(__file__).resolve().parent.parent / "examples" / "counter_sync.py"


def load_example():
    spec = importlib.util.spec_from_file_location("counter_sync", EXAMPLE)
    mod = importlib.util.module_from_spec(spec)
    sys.modules["counter_sync"] = mod
    spec.loader.exec_module(mod)
    return mod


def test_example_two_replicas_climb(tmp_path):
    ex = load_example()

    async def go():
        v1 = await ex.run(str(tmp_path), "dev-a", "pw", compact=False)
        # dev-b joins the same remote, must see dev-a's write and go one up
        v2 = await ex.run(str(tmp_path), "dev-b", "pw", compact=True)
        # dev-a runs again after dev-b's compaction: resumes from the snapshot
        v3 = await ex.run(str(tmp_path), "dev-a", "pw", compact=False)
        return v1, v2, v3

    v1, v2, v3 = asyncio.run(go())
    assert (v1, v2, v3) == (1, 2, 3)

"""The examples/ app as a smoke test — the reference uses its example the
same way (SURVEY.md §4: example-as-smoke-test), but with assertions added."""

import asyncio
import importlib.util
import sys
from pathlib import Path

EXAMPLES = Path(__file__).resolve().parent.parent / "examples"


def load_example(name="counter_sync"):
    spec = importlib.util.spec_from_file_location(name, EXAMPLES / f"{name}.py")
    mod = importlib.util.module_from_spec(spec)
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


def test_example_two_replicas_climb(tmp_path):
    ex = load_example()

    async def go():
        v1 = await ex.run(str(tmp_path), "dev-a", "pw", compact=False)
        # dev-b joins the same remote, must see dev-a's write and go one up
        v2 = await ex.run(str(tmp_path), "dev-b", "pw", compact=True)
        # dev-a runs again after dev-b's compaction: resumes from the snapshot
        v3 = await ex.run(str(tmp_path), "dev-a", "pw", compact=False)
        return v1, v2, v3

    v1, v2, v3 = asyncio.run(go())
    assert (v1, v2, v3) == (1, 2, 3)


def test_todo_example_flow(tmp_path):
    """The todo example's full command surface: add/done/list across
    replicas, key rotation mid-stream, compaction, fresh-replica read."""
    ex = load_example("todo_orset")
    import argparse

    def ns(local, cmd, item=None):
        return argparse.Namespace(
            data=str(tmp_path), local=local, passphrase="pw",
            cmd=cmd, item=item,
        )

    async def go():
        await ex.run(ns("laptop", "add", "buy milk"))
        await ex.run(ns("laptop", "add", "fix roof"))
        await ex.run(ns("phone", "done", "buy milk"))
        await ex.run(ns("laptop", "rotate-key"))
        await ex.run(ns("laptop", "add", "call mom"))
        await ex.run(ns("laptop", "compact"))
        # a brand-new replica reads only the compacted, re-sealed remote
        tablet = await ex.open_replica(str(tmp_path), "tablet", "pw")
        return tablet.with_state(lambda s: set(s.members()))

    items = asyncio.run(go())
    assert items == {b"fix roof", b"call mom"}

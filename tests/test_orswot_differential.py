"""Differential Orswot goldens: the crdts-v7 edge cases as explicit
expected-state fixtures.

The reference delegates set semantics to the external ``crdts`` crate v7
(Orswot with per-entry causal birth contexts; observable at
crdt-enc/src/lib.rs:460-466 `state.merge`, lib.rs:533-539 `state.apply`,
and the Keys CRDT's add-ctx protocol at key_cryptor.rs:72-82).  This
framework re-designed the representation tombstone-free (dense planes,
models/orset.py) — these fixtures pin that the OBSERVABLE behavior on the
crate's nasty cases is the Orswot behavior, with the expected outcome of
every case written out explicitly and justified, and verified on:

* the host model (per-op apply + CvRDT merge),
* the dense device fold (``ops.orset_fold`` → planes → state),
* the sparse host fold twin (``ops.orset_fold_sparse_host``),
* the device CvRDT merge (``ops.orset_merge``) for the merge cases.

Every case also checks merge commutativity and idempotence on its
states — order must never show in the canonical bytes.
"""

import numpy as np
import pytest

from crdt_enc_tpu import ops as K
from crdt_enc_tpu.models import ORSet, canonical_bytes
from crdt_enc_tpu.models.orset import AddOp, RmOp
from crdt_enc_tpu.models.vclock import Dot, VClock

A, B, C = b"\x0a" * 16, b"\x0b" * 16, b"\x0c" * 16


# ---- harness ---------------------------------------------------------------


def fold_host(ops, base=None):
    s = ORSet() if base is None else ORSet.from_obj(base.to_obj())
    for op in ops:
        s.apply(op)
    return s


def fold_dense(ops, base=None):
    """The device fold path: columns → orset_fold → planes → state."""
    base = ORSet() if base is None else base
    members, replicas = K.Vocab(), K.Vocab()
    cols = K.orset_ops_to_columns(ops, members, replicas)
    K.orset_scan_vocab(base, members, replicas)
    E, R = len(members), len(replicas)
    clock0, add0, rm0 = K.orset_state_to_planes(base, members, replicas, scanned=True)
    clock, add, rm = K.orset_fold(
        clock0, add0, rm0, cols.kind, cols.member, cols.actor, cols.counter,
        num_members=E, num_replicas=R,
    )
    return K.orset_planes_to_state(
        np.asarray(clock), np.asarray(add), np.asarray(rm), members, replicas
    )


def fold_sparse(ops, base=None):
    """The sparse host fold twin."""
    base = ORSet() if base is None else ORSet.from_obj(base.to_obj())
    members, replicas = K.Vocab(), K.Vocab()
    cols = K.orset_ops_to_columns(ops, members, replicas)
    K.orset_scan_vocab(base, members, replicas)
    return K.orset_fold_sparse_host(
        base, cols.kind, cols.member, cols.actor, cols.counter, members, replicas
    )


FOLDS = [("host", fold_host), ("dense", fold_dense), ("sparse", fold_sparse)]


def merge_host(a, b):
    out = ORSet.from_obj(a.to_obj())
    out.merge(ORSet.from_obj(b.to_obj()))
    return out


def merge_device(a, b):
    members, replicas = K.Vocab(), K.Vocab()
    K.orset_scan_vocab(a, members, replicas)
    K.orset_scan_vocab(b, members, replicas)
    pa = K.orset_state_to_planes(a, members, replicas, scanned=True)
    pb = K.orset_state_to_planes(b, members, replicas, scanned=True)
    clock, add, rm = K.orset_merge(*pa, *pb)
    return K.orset_planes_to_state(
        np.asarray(clock), np.asarray(add), np.asarray(rm), members, replicas
    )


MERGES = [("host", merge_host), ("device", merge_device)]


def expect_state(clock: dict, entries: dict, deferred: dict) -> ORSet:
    s = ORSet()
    s.clock = VClock(dict(clock))
    s.entries = {m: dict(v) for m, v in entries.items()}
    s.deferred = {m: dict(v) for m, v in deferred.items()}
    return s


def assert_merge_laws(a, b, expected):
    """Both merge orders and self-merge must land on the expected bytes."""
    for name, merge in MERGES:
        ab = merge(a, b)
        ba = merge(b, a)
        assert canonical_bytes(ab) == canonical_bytes(expected), (name, "a⊔b")
        assert canonical_bytes(ba) == canonical_bytes(expected), (name, "b⊔a")
        assert canonical_bytes(merge(ab, ab)) == canonical_bytes(expected), (
            name, "idempotence",
        )


# ---- case 1: deferred remove with ctx beyond the clock --------------------


@pytest.mark.parametrize("fold_name,fold", FOLDS)
def test_deferred_remove_beyond_clock(fold_name, fold):
    """crdts Orswot: `rm` with a ctx the local clock hasn't seen is
    DEFERRED — it must not error, must not remove prematurely, and must
    kill exactly the observed dots when they arrive.

    B removes "m" having observed A's dot 5; this replica has seen
    nothing from A.  Expected: "m" absent, the horizon {A:5} pending.
    Then A's dots arrive: dot 5 is born dead (covered); dot 6 survives
    (observed-remove removes only observed dots — add-wins beyond)."""
    rm_only = [RmOp(b"m", VClock({A: 5}))]
    expected_pending = expect_state(
        clock={}, entries={}, deferred={b"m": {A: 5}}
    )
    got = fold(rm_only)
    assert canonical_bytes(got) == canonical_bytes(expected_pending), fold_name

    # the observed dot arrives later: dead on arrival (per-actor dot order
    # means dot 5 for "m" is the dot the remove observed)
    caught_up = rm_only + [AddOp(b"m", Dot(A, 5))]
    expected_covered = expect_state(
        clock={A: 5}, entries={}, deferred={}
    )
    got = fold(caught_up)
    assert canonical_bytes(got) == canonical_bytes(expected_covered), fold_name

    # a dot BEYOND the horizon wins (add-wins for unobserved dots)
    readd = caught_up + [AddOp(b"m", Dot(A, 6))]
    expected_readd = expect_state(
        clock={A: 6}, entries={b"m": {A: 6}}, deferred={}
    )
    got = fold(readd)
    assert canonical_bytes(got) == canonical_bytes(expected_readd), fold_name


def test_deferred_remove_via_merge_of_disjoint_states():
    """The deferred horizon must also resolve through the CvRDT merge:
    state X holds only the pending remove, state Y holds A's add of the
    same dot — their merge kills the entry (crdts' deferred-remove
    apply-on-merge behavior)."""
    x = fold_host([RmOp(b"m", VClock({A: 5}))])
    y = fold_host([AddOp(b"m", Dot(A, i)) for i in range(1, 6)])
    expected = expect_state(clock={A: 5}, entries={}, deferred={})
    assert_merge_laws(x, y, expected)


# ---- case 2: concurrent add/remove across 3 replicas ----------------------


@pytest.mark.parametrize("fold_name,fold", FOLDS)
def test_concurrent_add_remove_three_replicas(fold_name, fold):
    """A adds "m"; B removes it observing A's dot; C adds "m"
    concurrently (its own dot).  Orswot add-wins: the remove kills only
    the OBSERVED dot (A:1) — C's unobserved dot survives, so "m" is
    present with exactly C's birth dot."""
    ops = [
        AddOp(b"m", Dot(A, 1)),
        RmOp(b"m", VClock({A: 1})),  # B's remove, observed {A:1} only
        AddOp(b"m", Dot(C, 1)),  # concurrent with the remove
    ]
    expected = expect_state(
        clock={A: 1, C: 1}, entries={b"m": {C: 1}}, deferred={}
    )
    got = fold(ops)
    assert canonical_bytes(got) == canonical_bytes(expected), fold_name


def test_concurrent_add_remove_three_replicas_via_merge():
    """Same scenario through three independent replica states merged in
    every order — the replica boundary must not change the outcome."""
    sa = fold_host([AddOp(b"m", Dot(A, 1))])
    sb = merge_host(sa, ORSet())  # B saw A's add…
    sb.apply(RmOp(b"m", VClock({A: 1})))  # …and removed it
    sc = fold_host([AddOp(b"m", Dot(C, 1))])  # C never saw A or B

    expected = expect_state(
        clock={A: 1, C: 1}, entries={b"m": {C: 1}}, deferred={}
    )
    for x, y, z in [(sa, sb, sc), (sc, sb, sa), (sb, sc, sa)]:
        for name, merge in MERGES:
            got = merge(merge(x, y), z)
            assert canonical_bytes(got) == canonical_bytes(expected), (
                name, "order",
            )


# ---- case 3: re-add after observed remove ---------------------------------


@pytest.mark.parametrize("fold_name,fold", FOLDS)
def test_readd_after_observed_remove(fold_name, fold):
    """A adds (A:1); B removes observing {A:1}; A re-adds with a fresh
    dot (A:2).  The re-add must survive — its dot was never observed by
    the remove — and the old dot must not resurrect."""
    ops = [
        AddOp(b"m", Dot(A, 1)),
        RmOp(b"m", VClock({A: 1})),
        AddOp(b"m", Dot(A, 2)),
    ]
    expected = expect_state(
        clock={A: 2}, entries={b"m": {A: 2}}, deferred={}
    )
    got = fold(ops)
    assert canonical_bytes(got) == canonical_bytes(expected), fold_name


def test_removed_entry_does_not_resurrect_on_stale_merge():
    """Clock-filter regression: a replica that removed "m" (clock covers
    the dot, entry gone) merged with a STALE replica still holding the
    dot must keep "m" absent — the stale holder's dot is 'seen but not
    held' on the fresh side, so it is dead (the tombstone-free design's
    core claim: the clock IS the tombstone)."""
    fresh = fold_host([AddOp(b"m", Dot(A, 1)), RmOp(b"m", VClock({A: 1}))])
    stale = fold_host([AddOp(b"m", Dot(A, 1))])
    expected = expect_state(clock={A: 1}, entries={}, deferred={})
    assert_merge_laws(fresh, stale, expected)


# ---- case 4: merge of disjoint-clock states -------------------------------


def test_merge_disjoint_clock_states():
    """States with non-overlapping actors and members: the merge is the
    plain union — nothing is filtered because neither clock covers the
    other's dots."""
    x = fold_host([AddOp(b"x", Dot(A, 1)), AddOp(b"both", Dot(A, 2))])
    y = fold_host([AddOp(b"y", Dot(B, 1)), AddOp(b"both", Dot(B, 2))])
    expected = expect_state(
        clock={A: 2, B: 2},
        entries={b"x": {A: 1}, b"both": {A: 2, B: 2}, b"y": {B: 1}},
        deferred={},
    )
    assert_merge_laws(x, y, expected)


def test_merge_disjoint_with_foreign_deferred_horizon():
    """A deferred horizon for an actor the OTHER side owns: X defers a
    remove observing B's dot 3; Y has B's dots 1..2 only.  The merge must
    keep the horizon pending (Y hasn't caught up) and still kill B's
    held dots ≤ 3."""
    x = fold_host([RmOp(b"m", VClock({B: 3}))])
    y = fold_host([AddOp(b"m", Dot(B, 1)), AddOp(b"k", Dot(B, 2))])
    expected = expect_state(
        clock={B: 2},
        entries={b"k": {B: 2}},
        deferred={b"m": {B: 3}},  # horizon still ahead of the clock
    )
    assert_merge_laws(x, y, expected)


# ---- the keys-CRDT usage shape (key_cryptor.rs:72-82) ---------------------


@pytest.mark.parametrize("fold_name,fold", FOLDS)
def test_add_ctx_protocol_shape(fold_name, fold):
    """The reference's only first-party Orswot user is the Keys CRDT:
    every insert is `add_ctx` (derive dot from the local read ctx) and
    keys are never removed.  Grow-only inserts from concurrent actors
    must union losslessly."""
    s1 = ORSet()
    ops = []
    for i, actor in enumerate([A, B, A, C, B]):
        op = s1.add_ctx(actor, b"key-%d" % i)
        s1.apply(op)
        ops.append(op)
    expected_members = [b"key-%d" % i for i in range(5)]
    got = fold(ops)
    assert got.members() == expected_members, fold_name
    assert canonical_bytes(got) == canonical_bytes(s1), fold_name

"""Parity: the Pallas LWW winner-selection fold (ops/pallas_lww.py)
must match the XLA cascade fold (ops/lww.py lww_fold) — which the
accelerator/bench already pin byte-identical to the host LWWMap — on
every shape the router can hand it.  Interpret mode on CPU; the MXU
path is exercised by benchmarks/suite.py config 4 on TPU."""

from __future__ import annotations

import numpy as np
import pytest

from crdt_enc_tpu.ops.lww import lww_fold, ts_split
from crdt_enc_tpu.ops.pallas_lww import lww_fold_pallas, lww_tile_cap


def _run_both(key, ts_hi, ts_lo, actor, value, K, V, win_mode="cond"):
    ref = lww_fold(
        key, ts_hi, ts_lo, actor, value, num_keys=K, num_values=V
    )
    got = lww_fold_pallas(
        key, ts_hi, ts_lo, actor, value, num_keys=K, num_values=V,
        tile_cap=lww_tile_cap(key, K), interpret=True, win_mode=win_mode,
    )
    for r, g, name in zip(ref, got, ("hi", "lo", "actor", "value", "present")):
        np.testing.assert_array_equal(
            np.asarray(r), np.asarray(g), err_msg=name
        )


def _gen(N, K, R, V, seed, ts_max=10 ** 12, pad_frac=0.05):
    rng = np.random.default_rng(seed)
    key = rng.integers(0, K, N, dtype=np.int32)
    key = np.where(rng.random(N) < pad_frac, K, key).astype(np.int32)
    hi, lo = ts_split(rng.integers(0, ts_max, N))
    actor = rng.integers(0, R, N, dtype=np.int32)
    value = rng.integers(0, V, N, dtype=np.int32)
    return key, hi, lo, actor, value


@pytest.mark.parametrize("seed", range(3))
@pytest.mark.parametrize(
    "N,K,R,V",
    [
        (500, 300, 20, 10),       # K < one tile
        (800, 16384, 8, 5),       # K == exactly one tile
        (1200, 20000, 30, 50),    # two tiles, second partial
        (300, 40000, 4, 3),       # sparse keys across three tiles
    ],
)
def test_parity_random(N, K, R, V, seed):
    _run_both(*_gen(N, K, R, V, seed), K, V)


def test_parity_heavy_ties():
    # many rows share (key, ts): the tie must resolve by packed
    # (actor, value) rank identically in both folds
    K, R, V = 64, 6, 4
    rng = np.random.default_rng(9)
    N = 600
    key = rng.integers(0, K, N, dtype=np.int32)
    hi = np.zeros(N, np.int32)
    lo = rng.integers(0, 3, N, dtype=np.int32)  # heavy ts collisions
    actor = rng.integers(0, R, N, dtype=np.int32)
    value = rng.integers(0, V, N, dtype=np.int32)
    _run_both(key, hi, lo, actor, value, K, V)


def test_parity_zero_ts_and_all_pad():
    # ts == 0 is a real timestamp; present-ness must not be confused
    # with the zero emitted by absent keys
    K, V = 10, 3
    key = np.array([0, 3, 10, 10], np.int32)  # two pad rows
    hi = np.zeros(4, np.int32)
    lo = np.zeros(4, np.int32)
    actor = np.array([1, 0, 0, 0], np.int32)
    value = np.array([2, 1, 0, 0], np.int32)
    _run_both(key, hi, lo, actor, value, K, V)
    # all padding: every key absent
    allpad = np.full(8, K, np.int32)
    _run_both(allpad, np.zeros(8, np.int32), np.zeros(8, np.int32),
              np.zeros(8, np.int32), np.zeros(8, np.int32), K, V)


def test_parity_ts_lo_saturated():
    # ts_lo == 2^31 - 1 (the max ts_split emits): a +1 present-offset on
    # the ts columns wrapped int32 here — present-ness must ride the
    # packed-rank column only (review finding, round 4)
    K, V = 8, 3
    hi31 = (1 << 31) - 1
    key = np.array([0, 0, 5], np.int32)
    hi = np.array([0, 7, hi31], np.int32)
    lo = np.array([hi31, hi31, hi31], np.int32)
    actor = np.array([1, 0, 0], np.int32)
    value = np.array([2, 1, 0], np.int32)
    _run_both(key, hi, lo, actor, value, K, V)


def test_parity_large_ts_hi_limbs():
    # timestamps big enough that every limb of ts_hi engages
    K, R, V = 128, 5, 7
    rng = np.random.default_rng(11)
    N = 400
    key = rng.integers(0, K, N, dtype=np.int32)
    hi, lo = ts_split(rng.integers(2 ** 55, 2 ** 61, N))
    actor = rng.integers(0, R, N, dtype=np.int32)
    value = rng.integers(0, V, N, dtype=np.int32)
    _run_both(key, hi, lo, actor, value, K, V)


from _hyp import given, settings, st  # hypothesis, or skip-stubs


@settings(max_examples=25, deadline=None)
@given(
    seed=st.integers(0, 2 ** 16),
    n=st.integers(1, 400),
    k=st.integers(1, 40000),
    r=st.integers(1, 40),
    v=st.integers(1, 40),
)
def test_parity_hypothesis(seed, n, k, r, v):
    _run_both(*_gen(n, k, r, v, seed), k, v)


def test_parity_select_window_mode():
    """The branchless window-load body (win_mode="select") must be
    byte-identical to the cond body on a multi-tile shape whose chunks
    straddle both windows."""
    _run_both(*_gen(1200, 20000, 30, 50, seed=9), 20000, 50,
              win_mode="select")


def test_limb_counts_quantized_and_bounded():
    """ADVICE r5 regression: the (hi, lo, av) limb tuple is a jit static
    arg of the Pallas LWW fold, so across arbitrarily varied batches the
    tuple space — and hence the compile count — must stay bounded.
    Quantization pins every component into [1, 4]; 200 randomized
    batches (including pathological maxima) may produce at most 64
    distinct tuples."""
    from crdt_enc_tpu.ops.pallas_lww import (
        _LIMB_COUNT_MAX, lww_column_maxima, lww_limbs,
        lww_limbs_from_maxima,
    )

    assert _LIMB_COUNT_MAX == 4
    rng = np.random.default_rng(0)
    seen = set()
    for trial in range(200):
        n = int(rng.integers(1, 50))
        hi = rng.integers(0, 2 ** 31 - 1, n).astype(np.int64)
        lo = rng.integers(0, 2 ** 31 - 1, n).astype(np.int64)
        actor = rng.integers(0, 2 ** 20, n).astype(np.int64)
        v = int(rng.integers(1, 1000))
        limbs = lww_limbs(hi, lo, actor, v)
        assert all(1 <= c <= 4 for c in limbs), limbs
        # the maxima round-trip matches the direct computation (callers
        # reusing columns cache the maxima and skip the O(N) passes)
        assert limbs == lww_limbs_from_maxima(
            *lww_column_maxima(hi, lo, actor, v)
        )
        seen.add(limbs)
    # empty columns stay inside the quantized range; a maximum past the
    # int32 contract RAISES rather than silently dropping high bits
    assert lww_limbs(np.zeros(0), np.zeros(0), np.zeros(0), 1) == (1, 1, 1)
    with pytest.raises(ValueError, match="limbs"):
        lww_limbs_from_maxima(2 ** 62, 1, 1)
    assert len(seen) <= 64

"""The overlapped streaming-compaction pipeline (ops/stream.py).

Three contracts pinned here:

* **overlap (the seam test)**: with trace events enabled, chunk k+1's
  ingest provably STARTS before chunk k's reduce/fold COMPLETES — the
  CPU-CI stand-in for the ≥3× end-to-end TPU claim (ISSUE 1 acceptance:
  on a box without a TPU the overlap is proved structurally, from span
  timestamps, not from wall-clock).
* **backpressure**: at most ``depth`` chunks are live host-side — chunk
  k+2's ingest cannot start until chunk k's reduce released its slot.
* **exactness**: the full pipeline (encrypted blobs → decrypt → decode →
  columnarize → fold) produces a byte-identical state to the whole-batch
  fold and to the per-op host reference.
"""

from __future__ import annotations

import secrets
import threading
import time

import numpy as np
import pytest

from crdt_enc_tpu import ops as K
from crdt_enc_tpu.utils import codec, trace


def _native_crypto_or_skip():
    from crdt_enc_tpu import native

    try:
        native.load()
    except RuntimeError as e:
        pytest.skip(f"native crypto library unavailable: {e}")


def _events_by_name(name):
    return sorted(
        (e for e in trace.events() if e["name"] == name),
        key=lambda e: e["meta"],
    )


# --------------------------------------------------------------- seam tests


def test_ingest_overlaps_reduce_seam():
    """Chunk k+1's ingest starts BEFORE chunk k's reduce completes: the
    producer/consumer overlap, proved from span timestamps with stage
    durations pinned by sleeps (deterministic on any box)."""
    trace.reset()
    trace.enable_events()
    try:
        def ingest(span, k):
            time.sleep(0.02)
            return span

        def reduce(item, k):
            time.sleep(0.05)

        K.run_ingest_pipeline(list(range(4)), ingest, reduce, depth=2)
    finally:
        trace.enable_events(False)
    ingests = _events_by_name("stream.ingest")
    reduces = _events_by_name("stream.reduce")
    assert [e["meta"] for e in ingests] == [0, 1, 2, 3]
    assert [e["meta"] for e in reduces] == [0, 1, 2, 3]
    overlapped = [
        k for k in range(3)
        if ingests[k + 1]["t0"] < reduces[k]["t1"]
    ]
    # with 20ms ingests and 50ms reduces EVERY interior chunk overlaps;
    # ≥1 required so scheduler noise can't flake the assertion
    assert overlapped, (
        "no chunk's ingest started before the previous chunk's reduce "
        f"finished: ingests={ingests} reduces={reduces}"
    )


def test_backpressure_bounds_live_chunks():
    """Chunk k+2's ingest must NOT start before chunk k's reduce has
    released its slot (BoundedSemaphore(depth=2)) — the at-most-two-
    chunks-of-host-memory guarantee."""
    trace.reset()
    trace.enable_events()
    try:
        def ingest(span, k):
            return span

        def reduce(item, k):
            time.sleep(0.03)

        K.run_ingest_pipeline(list(range(5)), ingest, reduce, depth=2)
    finally:
        trace.enable_events(False)
    ingests = _events_by_name("stream.ingest")
    reduces = _events_by_name("stream.reduce")
    for k in range(len(ingests) - 2):
        assert ingests[k + 2]["t0"] >= reduces[k]["t1"], (
            f"chunk {k + 2} ingested before chunk {k}'s slot was released"
        )


def test_h2d_issued_before_previous_fold_dispatch():
    """The consumer issues chunk k+1's device transfer BEFORE dispatching
    chunk k's donated fold (fold_chunks_overlapped's double-buffer
    discipline), so the copy rides under the in-flight fold."""
    R, E, rows = 3, 4, 8
    kind = np.zeros(24, np.int8)
    member = (np.arange(24) % E).astype(np.int32)
    actor = (np.arange(24) % R).astype(np.int32)
    counter = ((np.arange(24) // R) + 1).astype(np.int32)
    trace.reset()
    trace.enable_events()
    try:
        pool = K.ChunkPool(rows, depth=2)
        planes = K.orset_fold_stream(
            np.zeros(R, np.int32),
            np.zeros((E, R), np.int32),
            np.zeros((E, R), np.int32),
            K.iter_orset_chunks(kind, member, actor, counter, rows, R,
                                pool=pool),
            num_members=E, num_replicas=R, pool=pool,
        )
        K.planes_to_host(planes)
    finally:
        trace.enable_events(False)
    h2d = _events_by_name("stream.h2d")
    folds = _events_by_name("stream.fold")
    assert len(h2d) == 3 and len(folds) == 3
    for k in range(len(folds) - 1):
        assert h2d[k + 1]["t1"] <= folds[k]["t0"], (
            f"fold {k} dispatched before chunk {k + 1}'s transfer was issued"
        )


def test_producer_error_propagates():
    def ingest(span, k):
        if k == 1:
            raise ValueError("boom")
        return span

    with pytest.raises(K.PipelineError) as ei:
        K.run_ingest_pipeline(list(range(3)), ingest, lambda item, k: None)
    assert isinstance(ei.value.__cause__, ValueError)


def test_consumer_error_stops_producer():
    ingested = []

    def ingest(span, k):
        ingested.append(k)
        return span

    def reduce(item, k):
        raise RuntimeError("reduce failed")

    with pytest.raises(RuntimeError, match="reduce failed"):
        K.run_ingest_pipeline(list(range(50)), ingest, reduce, depth=2)
    # backpressure kept the producer from racing ahead of the failure
    assert len(ingested) <= 4
    # ... and the producer thread itself wound down (the pipeline joins
    # it on exit; poll briefly in case the runtime is slow to reap)
    _assert_no_producer_threads()


def _assert_no_producer_threads():
    deadline = time.time() + 5.0
    while time.time() < deadline and any(
        t.name.startswith("crdt-ingest-producer") and t.is_alive()
        for t in threading.enumerate()
    ):
        time.sleep(0.01)
    leaked = [
        t.name
        for t in threading.enumerate()
        if t.name.startswith("crdt-ingest-producer") and t.is_alive()
    ]
    assert not leaked, f"leaked producer threads: {leaked}"


# ----------------------------------------------------------- chunk staging


def test_pooled_chunks_equal_plain_chunks():
    """Pool-staged chunk iteration (pre-allocated buffers, sentinel
    padding) yields exactly the chunks the allocating path yields."""
    rng = np.random.default_rng(3)
    n, R, E, rows = 37, 5, 6, 8
    kind = rng.integers(0, 2, n).astype(np.int8)
    member = rng.integers(0, E, n).astype(np.int32)
    actor = rng.integers(0, R, n).astype(np.int32)
    counter = rng.integers(1, 50, n).astype(np.int32)
    plain = list(K.iter_orset_chunks(kind, member, actor, counter, rows, R))
    pool = K.ChunkPool(rows, depth=2)
    for i, bufs in enumerate(
        K.iter_orset_chunks(kind, member, actor, counter, rows, R, pool=pool)
    ):
        for got, want in zip(bufs, plain[i]):
            np.testing.assert_array_equal(got, want)
        pool.release(bufs)


def test_overlapped_stream_fold_matches_whole_batch():
    """orset_fold_stream with the overlapped loop + pool ≡ one whole-batch
    orset_fold on the same columns (plane-exact).  The op stream honors
    the causal-delivery contract the chunked fold assumes (per-actor
    counters arrive in version order — core.py _read_remote_ops): adds
    are each actor's next dot, removes carry the horizon seen so far."""
    rng = np.random.default_rng(11)
    n, R, E, rows = 301, 7, 9, 64
    kind = rng.integers(0, 2, n).astype(np.int8)
    member = rng.integers(0, E, n).astype(np.int32)
    actor = rng.integers(0, R, n).astype(np.int32)
    counter = np.zeros(n, np.int32)
    seen = np.zeros(R, np.int64)
    for i in range(n):
        a = actor[i]
        if kind[i] == 0 or seen[a] == 0:
            kind[i] = 0
            seen[a] += 1
        counter[i] = seen[a]
    z = lambda *s: np.zeros(s, np.int32)  # noqa: E731
    pool = K.ChunkPool(rows, depth=2)
    planes = K.orset_fold_stream(
        z(R), z(E, R), z(E, R),
        K.iter_orset_chunks(kind, member, actor, counter, rows, R, pool=pool),
        num_members=E, num_replicas=R, pool=pool,
    )
    clock_s, add_s, rm_s = K.planes_to_host(planes)
    clock_w, add_w, rm_w = K.orset_fold(
        z(R), z(E, R), z(E, R), kind, member, actor, counter,
        num_members=E, num_replicas=R,
    )
    np.testing.assert_array_equal(clock_s, np.asarray(clock_w))
    np.testing.assert_array_equal(add_s, np.asarray(add_w))
    np.testing.assert_array_equal(rm_s, np.asarray(rm_w))


# ------------------------------------------------- end-to-end differential


def _encrypted_orset_workload(n_files=40, ops_per_file=6, R=5, E=12, seed=2):
    """Per-actor op files sealed with the native AEAD + the per-op host
    truth (apply order == file order, per-actor version order)."""
    from crdt_enc_tpu.backends.xchacha import encrypt_blob
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.models.orset import AddOp, RmOp
    from crdt_enc_tpu.models.vclock import Dot, VClock

    rng = np.random.default_rng(seed)
    key = secrets.token_bytes(32)
    actors = [bytes([a]) * 16 for a in range(1, R + 1)]
    counters = {a: 0 for a in range(R)}
    host = ORSet()
    blobs = []
    for f in range(n_files):
        a = f % R
        ops = []
        for _ in range(ops_per_file):
            m = int(rng.integers(0, E))
            if rng.random() < 0.75 or counters[a] == 0:
                counters[a] += 1
                ops.append([0, m, [actors[a], counters[a]]])
                host.apply(AddOp(m, Dot(actors[a], counters[a])))
            else:
                ops.append([1, m, {actors[a]: counters[a]}])
                host.apply(RmOp(m, VClock({actors[a]: counters[a]})))
        blobs.append(encrypt_blob(key, codec.pack(ops)))
    return key, blobs, actors, host


def test_streaming_pipeline_byte_identical_to_host():
    """ISSUE 1 acceptance: encrypted blobs → streaming pipeline → state is
    BYTE-identical to the per-op host reference AND to the whole-batch
    bulk fold, across chunking geometries."""
    _native_crypto_or_skip()
    from crdt_enc_tpu.backends.xchacha import decrypt_blobs
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.parallel import TpuAccelerator

    key, blobs, actors, host = _encrypted_orset_workload()
    host_bytes = codec.pack(host.to_obj())
    accel = TpuAccelerator()
    hint = sorted(actors)

    # whole-batch bulk fold (the previously-pinned path)
    whole = ORSet()
    assert accel.fold_payloads(
        whole, decrypt_blobs(key, blobs), actors_hint=hint
    )
    assert codec.pack(whole.to_obj()) == host_bytes

    for n_chunks in (1, 3, 8, len(blobs)):
        streamed = ORSet()
        ok = accel.fold_encrypted_stream(
            streamed, key, blobs, actors_hint=hint, n_chunks=n_chunks,
        )
        assert ok, f"pipeline declined at n_chunks={n_chunks}"
        assert codec.pack(streamed.to_obj()) == host_bytes, (
            f"divergence at n_chunks={n_chunks}"
        )


def test_streaming_pipeline_into_existing_state():
    """The pipeline folds INTO a non-empty replica exactly as the per-op
    path does (stale dots rejected, pre-existing entries honored)."""
    _native_crypto_or_skip()
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.models.orset import AddOp
    from crdt_enc_tpu.models.vclock import Dot
    from crdt_enc_tpu.parallel import TpuAccelerator

    key, blobs, actors, host = _encrypted_orset_workload(seed=9)
    pre = [(b"\x77" * 16, 1, 99), (b"\x78" * 16, 2, 5)]
    streamed = ORSet()
    for a, c, m in pre:
        host_op = AddOp(m, Dot(a, c))
        streamed.apply(host_op)
        host.apply(host_op)  # same op applied before the stream in both
    # NB: host had the stream's ops applied already in the builder, so
    # rebuild host truth in the right order: pre-ops THEN stream ops
    host2 = ORSet()
    for a, c, m in pre:
        host2.apply(AddOp(m, Dot(a, c)))
    from crdt_enc_tpu.backends.xchacha import decrypt_blobs
    from crdt_enc_tpu.models.orset import RmOp
    from crdt_enc_tpu.models.vclock import VClock

    for raw in decrypt_blobs(key, blobs):
        for o in codec.unpack(raw):
            if o[0] == 0:
                host2.apply(AddOp(o[1], Dot.from_obj(o[2])))
            else:
                host2.apply(RmOp(o[1], VClock.from_obj(o[2])))

    accel = TpuAccelerator()
    ok = accel.fold_encrypted_stream(
        streamed, key, blobs, actors_hint=sorted(actors), n_chunks=4,
    )
    assert ok
    assert codec.pack(streamed.to_obj()) == codec.pack(host2.to_obj())


def test_streaming_pipeline_counter_session():
    """fold_encrypted_stream is generic over session types: a PN-Counter
    ingest runs the same pipeline and equals the per-op reference."""
    _native_crypto_or_skip()
    from crdt_enc_tpu.backends.xchacha import encrypt_blob
    from crdt_enc_tpu.models import PNCounter
    from crdt_enc_tpu.parallel import TpuAccelerator

    key = secrets.token_bytes(32)
    actors = [bytes([a]) * 16 for a in range(1, 4)]
    host = PNCounter()
    blobs = []
    rng = np.random.default_rng(4)
    for f in range(12):
        a = f % 3
        ops = []
        for _ in range(5):
            sign, dot = (
                host.inc(actors[a]) if rng.random() < 0.7
                else host.dec(actors[a])
            )
            ops.append([int(sign), [dot.actor, dot.counter]])
            host.apply((sign, dot))
        blobs.append(encrypt_blob(key, codec.pack(ops)))
    streamed = PNCounter()
    accel = TpuAccelerator()
    ok = accel.fold_encrypted_stream(
        streamed, key, blobs, actors_hint=sorted(actors), n_chunks=3,
    )
    assert ok
    assert codec.pack(streamed.to_obj()) == codec.pack(host.to_obj())
    assert streamed.read() == host.read()


def test_streaming_pipeline_seam_on_real_path():
    """The real pipeline (native decrypt + decode in the producer) emits
    the stage spans the docs promise, and its ingest of some chunk k+1
    starts before reduce k completes once reduces are non-trivial."""
    _native_crypto_or_skip()
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.parallel import TpuAccelerator

    key, blobs, actors, host = _encrypted_orset_workload(
        n_files=60, ops_per_file=8
    )
    accel = TpuAccelerator()
    streamed = ORSet()
    trace.reset()
    trace.enable_events()
    try:
        ok = accel.fold_encrypted_stream(
            streamed, key, blobs, actors_hint=sorted(actors), n_chunks=6,
        )
    finally:
        trace.enable_events(False)
    assert ok
    names = {e["name"] for e in trace.events()}
    for required in ("stream.decrypt", "stream.decode", "stream.ingest",
                     "stream.reduce", "stream.finish"):
        assert required in names, f"missing stage span {required}"
    assert codec.pack(streamed.to_obj()) == codec.pack(host.to_obj())


# ------------------------------------------------- multi-producer fan-out


def test_producer_count_resolution(monkeypatch):
    """stream_producer_count: explicit request > env override > the
    cpu-count auto-tune (one producer per core, one core reserved for
    the consumer, floor 1 — the stale cap of 4 is gone: an idle
    many-core host scales with its cores)."""
    monkeypatch.delenv("CRDT_STREAM_PRODUCERS", raising=False)
    assert K.stream_producer_count(3) == 3
    auto = K.stream_producer_count()
    import os

    cpus = os.cpu_count() or 1
    assert auto == max(1, cpus - 1)
    monkeypatch.setenv("CRDT_STREAM_PRODUCERS", "7")
    assert K.stream_producer_count() == 7
    assert K.stream_producer_count(2) == 2  # explicit still wins
    monkeypatch.setenv("CRDT_STREAM_PRODUCERS", "not-a-number")
    assert K.stream_producer_count() == auto


def test_multi_producer_order_deterministic():
    """The sequencer re-emits chunks in strict index order whatever the
    workers' finish order — pinned with randomized per-chunk delays at
    several fan-out widths."""
    rng = np.random.default_rng(17)
    delays = rng.random(24) * 0.01
    for producers in (1, 2, 4):
        order = []

        def ingest(span, k):
            time.sleep(delays[k])
            return span * 10

        def reduce(item, k):
            order.append((k, item))

        K.run_ingest_pipeline(
            list(range(24)), ingest, reduce, producers=producers
        )
        assert order == [(k, 10 * k) for k in range(24)], (producers, order)


def test_multi_producer_lanes_and_gauge():
    """N workers run under numbered thread lanes, the stream_producers
    gauge records the pool width, and the fan-out spans
    (stream.producer.wait, stream.sequence) are emitted."""
    trace.reset()
    trace.enable_events()
    try:
        K.run_ingest_pipeline(
            list(range(8)),
            lambda span, k: time.sleep(0.005) or span,
            lambda item, k: time.sleep(0.002),
            producers=2,
        )
    finally:
        trace.enable_events(False)
    snap = trace.snapshot()
    assert snap["gauges"]["stream_producers"] == 2
    events = trace.events()
    names = {e["name"] for e in events}
    assert {"stream.producer.wait", "stream.sequence"} <= names
    lanes = {
        e["thread"] for e in events if e["name"] == "stream.ingest"
    }
    assert lanes == {"crdt-ingest-producer-0", "crdt-ingest-producer-1"}
    trace.reset()


def test_multi_producer_overlap_seam():
    """With 2 producers and slow reduces, some chunk's ingest still
    starts before the previous chunk's reduce completes — the same
    overlap proof the single-producer seam test pins."""
    trace.reset()
    trace.enable_events()
    try:
        K.run_ingest_pipeline(
            list(range(6)),
            lambda span, k: time.sleep(0.02) or span,
            lambda item, k: time.sleep(0.05),
            producers=2,
        )
    finally:
        trace.enable_events(False)
    ingests = _events_by_name("stream.ingest")
    reduces = _events_by_name("stream.reduce")
    assert [e["meta"] for e in reduces] == list(range(6))
    assert any(
        ingests[k + 1]["t0"] < reduces[k]["t1"] for k in range(5)
    ), "no overlap with 2 producers"


def test_multi_producer_backpressure_bound():
    """At most depth chunks are ever live host-side, stashed sequencer
    chunks included: chunk k+depth's ingest cannot start before chunk
    k's reduce released its slot."""
    trace.reset()
    trace.enable_events()
    depth = 3
    try:
        K.run_ingest_pipeline(
            list(range(8)),
            lambda span, k: span,
            lambda item, k: time.sleep(0.02),
            depth=depth,
            producers=2,
        )
    finally:
        trace.enable_events(False)
    ingests = _events_by_name("stream.ingest")
    reduces = _events_by_name("stream.reduce")
    for k in range(len(ingests) - depth):
        assert ingests[k + depth]["t0"] >= reduces[k]["t1"], (
            f"chunk {k + depth} ingested before chunk {k}'s slot released"
        )


def test_multi_producer_fault_injection():
    """The first failing producer cancels its peers and the pending
    sequencer slots: every chunk BEFORE the failed index is reduced in
    order, the failure surfaces as PipelineError with the original as
    __cause__, no worker thread leaks, and the pipeline is reusable
    afterwards (no deadlocked BoundedSemaphore state escapes)."""
    rng = np.random.default_rng(3)
    delays = rng.random(30) * 0.008
    reduced = []

    def ingest(span, k):
        time.sleep(delays[k])
        if k == 7:
            raise ValueError("producer boom")
        return span

    def reduce(item, k):
        reduced.append(k)

    with pytest.raises(K.PipelineError) as ei:
        K.run_ingest_pipeline(
            list(range(30)), ingest, reduce, producers=3
        )
    assert isinstance(ei.value.__cause__, ValueError)
    # deterministic drain: exactly the pre-failure prefix, in order
    assert reduced == list(range(7)), reduced
    _assert_no_producer_threads()
    # a fresh run right after the fault completes normally (nothing
    # leaked into module or interpreter state)
    order = []
    K.run_ingest_pipeline(
        list(range(10)), lambda s, k: s, lambda i, k: order.append(k),
        producers=3,
    )
    assert order == list(range(10))


def test_multi_producer_consumer_error_cancels_pool():
    """A consumer failure stops every producer at its next poll."""
    ingested = []

    def ingest(span, k):
        ingested.append(k)
        return span

    def reduce(item, k):
        raise RuntimeError("reduce failed")

    with pytest.raises(RuntimeError, match="reduce failed"):
        K.run_ingest_pipeline(
            list(range(50)), ingest, reduce, depth=4, producers=3
        )
    # backpressure bounds how far the pool ran ahead of the failure
    assert len(ingested) <= 8
    _assert_no_producer_threads()


def test_multi_producer_byte_identical_to_single():
    """ISSUE 3 acceptance (differential): the SAME encrypted span set
    folded with 1, 2, and 4 producers — with randomized producer delays
    injected ahead of the real decrypt — produces byte-identical states,
    all equal to the per-op host reference."""
    _native_crypto_or_skip()
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.parallel import TpuAccelerator

    key, blobs, actors, host = _encrypted_orset_workload(
        n_files=48, ops_per_file=7, seed=21
    )
    host_bytes = codec.pack(host.to_obj())
    accel = TpuAccelerator()
    hint = sorted(actors)
    rng = np.random.default_rng(9)
    delays = rng.random(12) * 0.01

    from crdt_enc_tpu.ops import stream as stream_mod

    real_pipeline = stream_mod.run_striped_ingest_pipeline

    def jittered_pipeline(spans, split_fn, stripe_fn, assemble_fn,
                          reduce_fn, **kw):
        def slow_stripe(stripe, k, s):
            time.sleep(delays[(k + s) % len(delays)])
            return stripe_fn(stripe, k, s)

        return real_pipeline(
            spans, split_fn, slow_stripe, assemble_fn, reduce_fn, **kw
        )

    results = {}
    for n_producers in (1, 2, 4):
        streamed = ORSet()
        stream_mod.run_striped_ingest_pipeline = jittered_pipeline
        try:
            ok = accel.fold_encrypted_stream(
                streamed, key, blobs, actors_hint=hint, n_chunks=8,
                n_producers=n_producers,
            )
        finally:
            stream_mod.run_striped_ingest_pipeline = real_pipeline
        assert ok, f"pipeline declined at n_producers={n_producers}"
        results[n_producers] = codec.pack(streamed.to_obj())
    for n_producers, got in results.items():
        assert got == host_bytes, f"divergence at n_producers={n_producers}"


# ------------------------------------------------- mesh-sharded streaming


def _mesh_or_skip():
    import jax

    if len(jax.devices()) < 8:
        pytest.skip("needs the 8-device virtual CPU mesh (conftest)")
    from crdt_enc_tpu.parallel import mesh as pmesh

    return pmesh.make_mesh((4, 2))


def test_sharded_stream_byte_identical_to_single_chip(monkeypatch):
    """ISSUE 3 acceptance (sharded differential): the SAME encrypted
    span set folded through the mesh-sharded streaming branch
    (session._device_feed_sharded → orset_fold_sharded, planes
    mp-sharded, chunks dp-sharded) and through the single-chip stream is
    byte-identical — both equal to the per-op host reference."""
    _native_crypto_or_skip()
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.parallel import TpuAccelerator, mesh as pmesh
    from crdt_enc_tpu.parallel import session as psession

    mesh = _mesh_or_skip()
    # tiny promotion threshold so the small workload leaves BUFFER mode
    monkeypatch.setattr(psession, "BUFFER_BYTES", 256)

    key, blobs, actors, host = _encrypted_orset_workload(
        n_files=60, ops_per_file=8, R=5, E=24, seed=13
    )
    host_bytes = codec.pack(host.to_obj())
    hint = sorted(actors)

    accel = TpuAccelerator(mesh=mesh)
    assert accel.sharded_stream  # auto-on with an active mesh

    # spy: the sharded fold step must actually run (not a silent
    # fallback to the single-chip or buffered route)
    calls = []
    real_step = pmesh.sharded_stream_fold_step

    def spy_step(*a, **kw):
        step = real_step(*a, **kw)

        def wrapped(*args):
            calls.append(1)
            return step(*args)

        return wrapped

    monkeypatch.setattr(pmesh, "sharded_stream_fold_step", spy_step)

    sharded = ORSet()
    ok = accel.fold_encrypted_stream(
        sharded, key, blobs, actors_hint=hint, n_chunks=6, n_producers=2,
    )
    assert ok and calls, "sharded streaming fold did not engage"
    assert codec.pack(sharded.to_obj()) == host_bytes

    single = ORSet()
    ok = TpuAccelerator().fold_encrypted_stream(
        single, key, blobs, actors_hint=hint, n_chunks=6,
    )
    assert ok
    assert codec.pack(single.to_obj()) == host_bytes


def test_sharded_stream_into_existing_state(monkeypatch):
    """The sharded stream's finish combine uses op-APPLY semantics
    against the live state (retire_rm=False partial reduction): remove
    horizons streamed through the mesh still kill pre-existing entries,
    and stale dots are still rejected."""
    _native_crypto_or_skip()
    from crdt_enc_tpu.backends.xchacha import decrypt_blobs
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.models.orset import AddOp, RmOp
    from crdt_enc_tpu.models.vclock import Dot, VClock
    from crdt_enc_tpu.parallel import TpuAccelerator
    from crdt_enc_tpu.parallel import session as psession

    mesh = _mesh_or_skip()
    monkeypatch.setattr(psession, "BUFFER_BYTES", 256)

    key, blobs, actors, _ = _encrypted_orset_workload(
        n_files=48, ops_per_file=8, R=4, E=16, seed=29
    )
    pre = [(b"\x77" * 16, 1, 3), (b"\x78" * 16, 2, 5)]
    streamed = ORSet()
    host = ORSet()
    for a, c, m in pre:
        op = AddOp(m, Dot(a, c))
        streamed.apply(op)
        host.apply(op)
    for raw in decrypt_blobs(key, blobs):
        for o in codec.unpack(raw):
            if o[0] == 0:
                host.apply(AddOp(o[1], Dot.from_obj(o[2])))
            else:
                host.apply(RmOp(o[1], VClock.from_obj(o[2])))

    accel = TpuAccelerator(mesh=mesh)
    ok = accel.fold_encrypted_stream(
        streamed, key, blobs, actors_hint=sorted(actors), n_chunks=5,
    )
    assert ok
    assert codec.pack(streamed.to_obj()) == codec.pack(host.to_obj())


def test_sharded_stream_gated_off_multiprocess(monkeypatch):
    """On a multi-host pod (jax.process_count() > 1) the sharded stream
    must NOT engage: its growth/finish combine pulls the mp-sharded
    planes to host, which only addresses local shards — those meshes
    keep the buffered whole-batch sharded fold."""
    import jax

    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.parallel import TpuAccelerator
    from crdt_enc_tpu.parallel import session as psession

    mesh = _mesh_or_skip()
    monkeypatch.setattr(psession, "BUFFER_BYTES", 64)
    monkeypatch.setattr(jax, "process_count", lambda: 2)
    accel = TpuAccelerator(mesh=mesh)
    assert accel.sharded_stream  # the toggle itself stays on...
    session = accel.open_fold_session(ORSet(), actors_hint=[b"\x01" * 16])
    n = 40
    decoded = (
        np.zeros(n, np.int8),
        np.arange(n, dtype=np.int32) % 8,
        np.arange(n, dtype=np.int32) % 3,
        np.arange(n, dtype=np.int32) + 1,
        [bytes([m]) for m in range(8)],
    )
    session.reduce_chunk(decoded)
    # ...but the session refuses the promotion (local-shard host pulls)
    assert session.mode == "buffer" and not session._d_sharded


def test_sharded_stream_toggle_off_stays_buffered(monkeypatch):
    """sharded_stream=False (or CRDT_SHARDED_STREAM=0) preserves the
    historical buffered-mesh session: no promotion, finish through the
    whole-batch sharded fold."""
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.parallel import TpuAccelerator
    from crdt_enc_tpu.parallel import session as psession

    mesh = _mesh_or_skip()
    monkeypatch.setattr(psession, "BUFFER_BYTES", 64)
    actors = [bytes([a]) * 16 for a in range(1, 4)]

    def feed_rows(accel):
        session = accel.open_fold_session(ORSet(), actors_hint=actors)
        # synthetic decoded chunks (kind, member_idx, actor_idx, counter,
        # member_objs) — enough rows to blow the 64-byte buffer twice
        for base in (0, 40):
            n = 40
            decoded = (
                np.zeros(n, np.int8),
                np.arange(n, dtype=np.int32) % 8,
                np.arange(n, dtype=np.int32) % 3,
                np.arange(n, dtype=np.int32) + 1 + base,
                [bytes([m]) for m in range(8)],
            )
            session.reduce_chunk(decoded)
        return session

    off = feed_rows(TpuAccelerator(mesh=mesh, sharded_stream=False))
    assert off.mode == "buffer" and not off._d_sharded

    on = feed_rows(TpuAccelerator(mesh=mesh))
    assert on.mode == "device_stream" and on._d_sharded

    monkeypatch.setenv("CRDT_SHARDED_STREAM", "0")
    env_off = TpuAccelerator(mesh=mesh)
    assert not env_off.sharded_stream


# ------------------------------------------- unified work queue (stripes)


def test_striped_order_deterministic_with_random_delays():
    """Stripes claimed by 1/2/4 producers with randomized stripe delays
    still reduce in strict chunk order, with each chunk's parts
    assembled in stripe order."""
    rng = np.random.default_rng(3)
    delays = rng.random(40) * 0.004

    for producers in (1, 2, 4):
        order = []

        def split(span, k):
            return [(k, s) for s in range(1 + k % 3)]

        def stripe(item, k, s):
            time.sleep(delays[(k * 3 + s) % len(delays)])
            assert item == (k, s)
            return ("part", k, s)

        def assemble(parts, span, k):
            assert parts == [("part", k, s) for s in range(1 + k % 3)]
            return ("chunk", k)

        def reduce(item, k):
            assert item == ("chunk", k)
            order.append(k)

        K.run_striped_ingest_pipeline(
            list(range(18)), split, stripe, assemble, reduce,
            producers=producers, inline=False,
        )
        assert order == list(range(18)), (producers, order)


def test_striped_giant_stripe_does_not_block_peers():
    """One slow stripe occupies one worker while a second worker keeps
    claiming OTHER stripes — the file-granular claim contract (the old
    chunk-granular pool serialized everything behind the giant)."""
    started = []
    release = threading.Event()

    def split(span, k):
        return [0, 1] if k == 0 else [0]

    def stripe(item, k, s):
        started.append((k, s))
        if (k, s) == (0, 0):
            assert release.wait(10.0)
        return (k, s)

    def assemble(parts, span, k):
        return k

    done = []

    def reduce(item, k):
        done.append(k)

    t = threading.Thread(
        target=lambda: K.run_striped_ingest_pipeline(
            list(range(4)), split, stripe, assemble, reduce,
            producers=2, inline=False,
        )
    )
    t.start()
    deadline = time.monotonic() + 10.0
    # the second worker must make progress past the stalled stripe
    while len(started) < 4 and time.monotonic() < deadline:
        time.sleep(0.005)
    assert len(started) >= 4, started
    assert not done  # chunk order: nothing reduces before chunk 0
    release.set()
    t.join(10.0)
    assert done == [0, 1, 2, 3]


def test_striped_fault_propagates_and_joins_workers():
    before = threading.active_count()

    def split(span, k):
        return [0, 1]

    def stripe(item, k, s):
        if (k, s) == (2, 1):
            raise ValueError("boom at (2,1)")
        return 0

    with pytest.raises(K.PipelineError) as ei:
        K.run_striped_ingest_pipeline(
            list(range(8)), split, stripe, lambda p, sp, k: 0,
            lambda i, k: None, producers=3, inline=False,
        )
    assert isinstance(ei.value.__cause__, ValueError)
    deadline = time.monotonic() + 5.0
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before


def test_striped_consumer_error_cancels_pool():
    before = threading.active_count()

    def reduce(item, k):
        if k == 1:
            raise RuntimeError("consumer dies")

    with pytest.raises(RuntimeError):
        K.run_striped_ingest_pipeline(
            list(range(30)), lambda sp, k: [0], lambda it, k, s: 0,
            lambda p, sp, k: 0, reduce, producers=3, inline=False,
        )
    deadline = time.monotonic() + 5.0
    while threading.active_count() > before and time.monotonic() < deadline:
        time.sleep(0.01)
    assert threading.active_count() <= before


def test_striped_empty_chunks_and_empty_split():
    """Zero spans is a no-op; a split returning [] still emits the chunk
    (assemble sees no parts) and order holds."""
    K.run_striped_ingest_pipeline(
        [], lambda sp, k: [0], lambda it, k, s: 0, lambda p, sp, k: 0,
        lambda i, k: None, producers=2, inline=False,
    )
    order = []
    K.run_striped_ingest_pipeline(
        list(range(5)),
        lambda sp, k: [] if k % 2 else [0],
        lambda it, k, s: "p",
        lambda parts, sp, k: (k, parts),
        lambda item, k: order.append(item),
        producers=2, inline=False,
    )
    assert order == [(k, ["p"] if k % 2 == 0 else []) for k in range(5)]


def test_striped_inline_auto_on_single_core(monkeypatch):
    """producers==1 on a 1-core host runs the whole pipeline inline —
    no worker threads — and still byte-identically (order + parts)."""
    import crdt_enc_tpu.ops.stream as stream_mod

    monkeypatch.setattr(stream_mod.os, "cpu_count", lambda: 1)
    spawned = []
    real_thread = threading.Thread

    class SpyThread(real_thread):
        def __init__(self, *a, **kw):
            spawned.append(kw.get("name"))
            super().__init__(*a, **kw)

    monkeypatch.setattr(stream_mod.threading, "Thread", SpyThread)
    order = []
    K.run_striped_ingest_pipeline(
        list(range(6)), lambda sp, k: [0, 1],
        lambda it, k, s: (k, s),
        lambda parts, sp, k: (k, parts),
        lambda item, k: order.append(item),
        producers=1,
    )
    assert order == [(k, [(k, 0), (k, 1)]) for k in range(6)]
    assert spawned == []  # inline: not a single worker thread
    # explicit inline=False still threads even on one core
    K.run_striped_ingest_pipeline(
        list(range(2)), lambda sp, k: [0], lambda it, k, s: 0,
        lambda p, sp, k: 0, lambda i, k: None, producers=1, inline=False,
    )
    assert spawned  # the forced path spawned its worker


def test_stream_counters_pinned_on_striped_path():
    """bytes_decrypted on the accel streaming front door equals EXACTLY
    the byte sum of the encrypted blobs (counted only after a stripe's
    decrypt succeeds), and the host/buffer regime issues zero h2d — the
    attribution marginals' inputs stay trustworthy (ISSUE 13 audit)."""
    _native_crypto_or_skip()
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.parallel import TpuAccelerator

    key, blobs, actors, host = _encrypted_orset_workload(seed=5)
    accel = TpuAccelerator()
    trace.reset()
    state = ORSet()
    assert accel.fold_encrypted_stream(
        state, key, blobs, actors_hint=sorted(actors), n_chunks=4,
    )
    snap = trace.snapshot()
    assert snap["counters"].get("bytes_decrypted", 0) == sum(
        len(b) for b in blobs
    )
    # tiny workload stays in the BUFFER regime; its one device hop is
    # the dense fold's state-plane upload — exactly clock (R·4) +
    # add/rm planes (2·E·R·4) for this E=12, R=5 shape.  A drift here
    # means an unaccounted (or double-counted) device hop appeared.
    assert snap["counters"].get("h2d_bytes", 0) == 5 * 4 + 2 * 12 * 5 * 4
    assert codec.pack(state.to_obj()) == codec.pack(host.to_obj())
    # a failed decrypt (wrong key) counts NOTHING
    trace.reset()
    from crdt_enc_tpu.backends.xchacha import AeadError

    with pytest.raises(AeadError):
        accel.fold_encrypted_stream(
            ORSet(), secrets.token_bytes(32), blobs,
            actors_hint=sorted(actors), n_chunks=4,
        )
    assert trace.snapshot()["counters"].get("bytes_decrypted", 0) == 0


def test_session_fresh_fast_init_matches_general_path():
    """The fresh-state sorted-hint fast init must agree with the general
    construction (actor table, R, clock0) and fold byte-identically when
    the hint arrives UNSORTED (general path) vs sorted (fast path)."""
    _native_crypto_or_skip()
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.parallel import TpuAccelerator
    from crdt_enc_tpu.parallel.session import OrsetFoldSession

    key, blobs, actors, host = _encrypted_orset_workload(seed=11)
    accel = TpuAccelerator()
    fast = OrsetFoldSession(accel, ORSet(), sorted(actors))
    slow = OrsetFoldSession(accel, ORSet(), list(reversed(sorted(actors))))
    assert fast.actors_sorted == slow.actors_sorted
    assert fast.R == slow.R
    assert (fast._clock0 == slow._clock0).all()

    # non-fresh: a state with a clock must land in _clock0 exactly
    seeded = ORSet()
    from crdt_enc_tpu.models.orset import AddOp
    from crdt_enc_tpu.models.vclock import Dot

    seeded.apply(AddOp(3, Dot(actors[1], 7)))
    sess = OrsetFoldSession(accel, seeded, sorted(actors))
    pos = sess.actors_sorted.index(actors[1])
    assert sess._clock0[pos] == 7

    results = {}
    for hint in (sorted(actors), list(reversed(sorted(actors)))):
        state = ORSet()
        assert accel.fold_encrypted_stream(
            state, key, blobs, actors_hint=hint, n_chunks=4
        )
        results[tuple(hint)] = codec.pack(state.to_obj())
    assert len(set(results.values())) == 1
    assert next(iter(results.values())) == codec.pack(host.to_obj())


def test_session_member_collision_declines_on_bytes_path():
    """1 == True as members: the bytes-keyed remap must decline exactly
    like the legacy object remap (the dense planes cannot represent the
    collision), and the caller's fallback still folds correctly."""
    _native_crypto_or_skip()
    from crdt_enc_tpu.backends.xchacha import encrypt_blob
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.parallel import TpuAccelerator

    key = secrets.token_bytes(32)
    actor = b"\x01" * 16
    blobs = [
        encrypt_blob(key, codec.pack([[0, 1, [actor, 1]]])),
        encrypt_blob(key, codec.pack([[0, True, [actor, 2]]])),
    ]
    accel = TpuAccelerator()
    state = ORSet()
    ok = accel.fold_encrypted_stream(
        state, key, blobs, actors_hint=[actor], n_chunks=1
    )
    assert not ok  # declined, state untouched — caller replays per-op
    assert not state.entries

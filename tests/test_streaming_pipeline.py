"""The overlapped streaming-compaction pipeline (ops/stream.py).

Three contracts pinned here:

* **overlap (the seam test)**: with trace events enabled, chunk k+1's
  ingest provably STARTS before chunk k's reduce/fold COMPLETES — the
  CPU-CI stand-in for the ≥3× end-to-end TPU claim (ISSUE 1 acceptance:
  on a box without a TPU the overlap is proved structurally, from span
  timestamps, not from wall-clock).
* **backpressure**: at most ``depth`` chunks are live host-side — chunk
  k+2's ingest cannot start until chunk k's reduce released its slot.
* **exactness**: the full pipeline (encrypted blobs → decrypt → decode →
  columnarize → fold) produces a byte-identical state to the whole-batch
  fold and to the per-op host reference.
"""

from __future__ import annotations

import secrets
import threading
import time

import numpy as np
import pytest

from crdt_enc_tpu import ops as K
from crdt_enc_tpu.utils import codec, trace


def _native_crypto_or_skip():
    from crdt_enc_tpu import native

    try:
        native.load()
    except RuntimeError as e:
        pytest.skip(f"native crypto library unavailable: {e}")


def _events_by_name(name):
    return sorted(
        (e for e in trace.events() if e["name"] == name),
        key=lambda e: e["meta"],
    )


# --------------------------------------------------------------- seam tests


def test_ingest_overlaps_reduce_seam():
    """Chunk k+1's ingest starts BEFORE chunk k's reduce completes: the
    producer/consumer overlap, proved from span timestamps with stage
    durations pinned by sleeps (deterministic on any box)."""
    trace.reset()
    trace.enable_events()
    try:
        def ingest(span, k):
            time.sleep(0.02)
            return span

        def reduce(item, k):
            time.sleep(0.05)

        K.run_ingest_pipeline(list(range(4)), ingest, reduce, depth=2)
    finally:
        trace.enable_events(False)
    ingests = _events_by_name("stream.ingest")
    reduces = _events_by_name("stream.reduce")
    assert [e["meta"] for e in ingests] == [0, 1, 2, 3]
    assert [e["meta"] for e in reduces] == [0, 1, 2, 3]
    overlapped = [
        k for k in range(3)
        if ingests[k + 1]["t0"] < reduces[k]["t1"]
    ]
    # with 20ms ingests and 50ms reduces EVERY interior chunk overlaps;
    # ≥1 required so scheduler noise can't flake the assertion
    assert overlapped, (
        "no chunk's ingest started before the previous chunk's reduce "
        f"finished: ingests={ingests} reduces={reduces}"
    )


def test_backpressure_bounds_live_chunks():
    """Chunk k+2's ingest must NOT start before chunk k's reduce has
    released its slot (BoundedSemaphore(depth=2)) — the at-most-two-
    chunks-of-host-memory guarantee."""
    trace.reset()
    trace.enable_events()
    try:
        def ingest(span, k):
            return span

        def reduce(item, k):
            time.sleep(0.03)

        K.run_ingest_pipeline(list(range(5)), ingest, reduce, depth=2)
    finally:
        trace.enable_events(False)
    ingests = _events_by_name("stream.ingest")
    reduces = _events_by_name("stream.reduce")
    for k in range(len(ingests) - 2):
        assert ingests[k + 2]["t0"] >= reduces[k]["t1"], (
            f"chunk {k + 2} ingested before chunk {k}'s slot was released"
        )


def test_h2d_issued_before_previous_fold_dispatch():
    """The consumer issues chunk k+1's device transfer BEFORE dispatching
    chunk k's donated fold (fold_chunks_overlapped's double-buffer
    discipline), so the copy rides under the in-flight fold."""
    R, E, rows = 3, 4, 8
    kind = np.zeros(24, np.int8)
    member = (np.arange(24) % E).astype(np.int32)
    actor = (np.arange(24) % R).astype(np.int32)
    counter = ((np.arange(24) // R) + 1).astype(np.int32)
    trace.reset()
    trace.enable_events()
    try:
        pool = K.ChunkPool(rows, depth=2)
        planes = K.orset_fold_stream(
            np.zeros(R, np.int32),
            np.zeros((E, R), np.int32),
            np.zeros((E, R), np.int32),
            K.iter_orset_chunks(kind, member, actor, counter, rows, R,
                                pool=pool),
            num_members=E, num_replicas=R, pool=pool,
        )
        K.planes_to_host(planes)
    finally:
        trace.enable_events(False)
    h2d = _events_by_name("stream.h2d")
    folds = _events_by_name("stream.fold")
    assert len(h2d) == 3 and len(folds) == 3
    for k in range(len(folds) - 1):
        assert h2d[k + 1]["t1"] <= folds[k]["t0"], (
            f"fold {k} dispatched before chunk {k + 1}'s transfer was issued"
        )


def test_producer_error_propagates():
    def ingest(span, k):
        if k == 1:
            raise ValueError("boom")
        return span

    with pytest.raises(K.PipelineError) as ei:
        K.run_ingest_pipeline(list(range(3)), ingest, lambda item, k: None)
    assert isinstance(ei.value.__cause__, ValueError)


def test_consumer_error_stops_producer():
    ingested = []

    def ingest(span, k):
        ingested.append(k)
        return span

    def reduce(item, k):
        raise RuntimeError("reduce failed")

    with pytest.raises(RuntimeError, match="reduce failed"):
        K.run_ingest_pipeline(list(range(50)), ingest, reduce, depth=2)
    # backpressure kept the producer from racing ahead of the failure
    assert len(ingested) <= 4
    # ... and the producer thread itself wound down (the pipeline joins
    # it on exit; poll briefly in case the runtime is slow to reap)
    deadline = time.time() + 5.0
    while time.time() < deadline and any(
        t.name == "crdt-ingest-producer" and t.is_alive()
        for t in threading.enumerate()
    ):
        time.sleep(0.01)
    assert not any(
        t.name == "crdt-ingest-producer" and t.is_alive()
        for t in threading.enumerate()
    )


# ----------------------------------------------------------- chunk staging


def test_pooled_chunks_equal_plain_chunks():
    """Pool-staged chunk iteration (pre-allocated buffers, sentinel
    padding) yields exactly the chunks the allocating path yields."""
    rng = np.random.default_rng(3)
    n, R, E, rows = 37, 5, 6, 8
    kind = rng.integers(0, 2, n).astype(np.int8)
    member = rng.integers(0, E, n).astype(np.int32)
    actor = rng.integers(0, R, n).astype(np.int32)
    counter = rng.integers(1, 50, n).astype(np.int32)
    plain = list(K.iter_orset_chunks(kind, member, actor, counter, rows, R))
    pool = K.ChunkPool(rows, depth=2)
    for i, bufs in enumerate(
        K.iter_orset_chunks(kind, member, actor, counter, rows, R, pool=pool)
    ):
        for got, want in zip(bufs, plain[i]):
            np.testing.assert_array_equal(got, want)
        pool.release(bufs)


def test_overlapped_stream_fold_matches_whole_batch():
    """orset_fold_stream with the overlapped loop + pool ≡ one whole-batch
    orset_fold on the same columns (plane-exact).  The op stream honors
    the causal-delivery contract the chunked fold assumes (per-actor
    counters arrive in version order — core.py _read_remote_ops): adds
    are each actor's next dot, removes carry the horizon seen so far."""
    rng = np.random.default_rng(11)
    n, R, E, rows = 301, 7, 9, 64
    kind = rng.integers(0, 2, n).astype(np.int8)
    member = rng.integers(0, E, n).astype(np.int32)
    actor = rng.integers(0, R, n).astype(np.int32)
    counter = np.zeros(n, np.int32)
    seen = np.zeros(R, np.int64)
    for i in range(n):
        a = actor[i]
        if kind[i] == 0 or seen[a] == 0:
            kind[i] = 0
            seen[a] += 1
        counter[i] = seen[a]
    z = lambda *s: np.zeros(s, np.int32)  # noqa: E731
    pool = K.ChunkPool(rows, depth=2)
    planes = K.orset_fold_stream(
        z(R), z(E, R), z(E, R),
        K.iter_orset_chunks(kind, member, actor, counter, rows, R, pool=pool),
        num_members=E, num_replicas=R, pool=pool,
    )
    clock_s, add_s, rm_s = K.planes_to_host(planes)
    clock_w, add_w, rm_w = K.orset_fold(
        z(R), z(E, R), z(E, R), kind, member, actor, counter,
        num_members=E, num_replicas=R,
    )
    np.testing.assert_array_equal(clock_s, np.asarray(clock_w))
    np.testing.assert_array_equal(add_s, np.asarray(add_w))
    np.testing.assert_array_equal(rm_s, np.asarray(rm_w))


# ------------------------------------------------- end-to-end differential


def _encrypted_orset_workload(n_files=40, ops_per_file=6, R=5, E=12, seed=2):
    """Per-actor op files sealed with the native AEAD + the per-op host
    truth (apply order == file order, per-actor version order)."""
    from crdt_enc_tpu.backends.xchacha import encrypt_blob
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.models.orset import AddOp, RmOp
    from crdt_enc_tpu.models.vclock import Dot, VClock

    rng = np.random.default_rng(seed)
    key = secrets.token_bytes(32)
    actors = [bytes([a]) * 16 for a in range(1, R + 1)]
    counters = {a: 0 for a in range(R)}
    host = ORSet()
    blobs = []
    for f in range(n_files):
        a = f % R
        ops = []
        for _ in range(ops_per_file):
            m = int(rng.integers(0, E))
            if rng.random() < 0.75 or counters[a] == 0:
                counters[a] += 1
                ops.append([0, m, [actors[a], counters[a]]])
                host.apply(AddOp(m, Dot(actors[a], counters[a])))
            else:
                ops.append([1, m, {actors[a]: counters[a]}])
                host.apply(RmOp(m, VClock({actors[a]: counters[a]})))
        blobs.append(encrypt_blob(key, codec.pack(ops)))
    return key, blobs, actors, host


def test_streaming_pipeline_byte_identical_to_host():
    """ISSUE 1 acceptance: encrypted blobs → streaming pipeline → state is
    BYTE-identical to the per-op host reference AND to the whole-batch
    bulk fold, across chunking geometries."""
    _native_crypto_or_skip()
    from crdt_enc_tpu.backends.xchacha import decrypt_blobs
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.parallel import TpuAccelerator

    key, blobs, actors, host = _encrypted_orset_workload()
    host_bytes = codec.pack(host.to_obj())
    accel = TpuAccelerator()
    hint = sorted(actors)

    # whole-batch bulk fold (the previously-pinned path)
    whole = ORSet()
    assert accel.fold_payloads(
        whole, decrypt_blobs(key, blobs), actors_hint=hint
    )
    assert codec.pack(whole.to_obj()) == host_bytes

    for n_chunks in (1, 3, 8, len(blobs)):
        streamed = ORSet()
        ok = accel.fold_encrypted_stream(
            streamed, key, blobs, actors_hint=hint, n_chunks=n_chunks,
        )
        assert ok, f"pipeline declined at n_chunks={n_chunks}"
        assert codec.pack(streamed.to_obj()) == host_bytes, (
            f"divergence at n_chunks={n_chunks}"
        )


def test_streaming_pipeline_into_existing_state():
    """The pipeline folds INTO a non-empty replica exactly as the per-op
    path does (stale dots rejected, pre-existing entries honored)."""
    _native_crypto_or_skip()
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.models.orset import AddOp
    from crdt_enc_tpu.models.vclock import Dot
    from crdt_enc_tpu.parallel import TpuAccelerator

    key, blobs, actors, host = _encrypted_orset_workload(seed=9)
    pre = [(b"\x77" * 16, 1, 99), (b"\x78" * 16, 2, 5)]
    streamed = ORSet()
    for a, c, m in pre:
        host_op = AddOp(m, Dot(a, c))
        streamed.apply(host_op)
        host.apply(host_op)  # same op applied before the stream in both
    # NB: host had the stream's ops applied already in the builder, so
    # rebuild host truth in the right order: pre-ops THEN stream ops
    host2 = ORSet()
    for a, c, m in pre:
        host2.apply(AddOp(m, Dot(a, c)))
    from crdt_enc_tpu.backends.xchacha import decrypt_blobs
    from crdt_enc_tpu.models.orset import RmOp
    from crdt_enc_tpu.models.vclock import VClock

    for raw in decrypt_blobs(key, blobs):
        for o in codec.unpack(raw):
            if o[0] == 0:
                host2.apply(AddOp(o[1], Dot.from_obj(o[2])))
            else:
                host2.apply(RmOp(o[1], VClock.from_obj(o[2])))

    accel = TpuAccelerator()
    ok = accel.fold_encrypted_stream(
        streamed, key, blobs, actors_hint=sorted(actors), n_chunks=4,
    )
    assert ok
    assert codec.pack(streamed.to_obj()) == codec.pack(host2.to_obj())


def test_streaming_pipeline_counter_session():
    """fold_encrypted_stream is generic over session types: a PN-Counter
    ingest runs the same pipeline and equals the per-op reference."""
    _native_crypto_or_skip()
    from crdt_enc_tpu.backends.xchacha import encrypt_blob
    from crdt_enc_tpu.models import PNCounter
    from crdt_enc_tpu.parallel import TpuAccelerator

    key = secrets.token_bytes(32)
    actors = [bytes([a]) * 16 for a in range(1, 4)]
    host = PNCounter()
    blobs = []
    rng = np.random.default_rng(4)
    for f in range(12):
        a = f % 3
        ops = []
        for _ in range(5):
            sign, dot = (
                host.inc(actors[a]) if rng.random() < 0.7
                else host.dec(actors[a])
            )
            ops.append([int(sign), [dot.actor, dot.counter]])
            host.apply((sign, dot))
        blobs.append(encrypt_blob(key, codec.pack(ops)))
    streamed = PNCounter()
    accel = TpuAccelerator()
    ok = accel.fold_encrypted_stream(
        streamed, key, blobs, actors_hint=sorted(actors), n_chunks=3,
    )
    assert ok
    assert codec.pack(streamed.to_obj()) == codec.pack(host.to_obj())
    assert streamed.read() == host.read()


def test_streaming_pipeline_seam_on_real_path():
    """The real pipeline (native decrypt + decode in the producer) emits
    the stage spans the docs promise, and its ingest of some chunk k+1
    starts before reduce k completes once reduces are non-trivial."""
    _native_crypto_or_skip()
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.parallel import TpuAccelerator

    key, blobs, actors, host = _encrypted_orset_workload(
        n_files=60, ops_per_file=8
    )
    accel = TpuAccelerator()
    streamed = ORSet()
    trace.reset()
    trace.enable_events()
    try:
        ok = accel.fold_encrypted_stream(
            streamed, key, blobs, actors_hint=sorted(actors), n_chunks=6,
        )
    finally:
        trace.enable_events(False)
    assert ok
    names = {e["name"] for e in trace.events()}
    for required in ("stream.decrypt", "stream.decode", "stream.ingest",
                     "stream.reduce", "stream.finish"):
        assert required in names, f"missing stage span {required}"
    assert codec.pack(streamed.to_obj()) == codec.pack(host.to_obj())

"""Population runner (ISSUE 18): P schedules through ONE shared
substrate, bit-identical to their serial runs.

The contract under test is the determinism law (docs/simulation.md
"Population runs"): a schedule's fingerprint and fault tallies must not
move when it runs concurrently with others through the shared
FoldService/accelerator — every RNG stream is per-(schedule, replica,
family, counter), so cooperative interleaving cannot shift a draw.
Around it: the wall-clock budget mode (gates STARTS, never kills a
lane), the explore→ddmin flow for violations found inside a population,
the fault×vocabulary co-fire matrix and its ``obs_report simcov``
renderer, the bench refusal guard + trend pickup, the shared-owner
service entry, the counter tap the per-lane quarantine tally rides, and
attribution's sim-span blindness.
"""

import asyncio
import json
import pathlib
import threading

import pytest

from crdt_enc_tpu.obs import attribution, fleet, runtime as obs_runtime, sink
from crdt_enc_tpu.sim import (
    CoFireMatrix,
    FaultConfig,
    PopulationReport,
    Schedule,
    Step,
    Violation,
    generate,
    run_budget,
    run_population,
    run_schedule,
    verify_serial_equality,
)
from crdt_enc_tpu.sim.coverage import VOCABULARIES
from crdt_enc_tpu.sim.population import PopulationSubstrate
from crdt_enc_tpu.sim.runner import SimResult
from crdt_enc_tpu.tools import obs_report
from crdt_enc_tpu.tools import sim as sim_cli
from crdt_enc_tpu.utils import trace

REPO = pathlib.Path(__file__).parent.parent


# -------------------------------------------------- the determinism law


def test_population_bit_identical_to_serial_mixed_vocabs():
    """THE contract: a mixed-vocabulary population (base, deltas,
    daemon+strong-reads lanes side by side) produces, per schedule, the
    exact fingerprint and fault tallies of its serial run — checked by
    the same verifier CI and the bench refusal guard use."""
    schedules = [
        generate(0, 3, 40, FaultConfig.all_faults(), members=6),
        generate(1, 3, 40, FaultConfig.all_faults(), members=6,
                 deltas=True),
        generate(2, 3, 40, FaultConfig.all_faults(), members=6,
                 daemon=True, strong_reads=True),
    ]
    report = run_population(schedules, population=2)
    assert [r.ok for r in report.results] == [True] * 3, report.violations
    # 3 schedules over 2 lanes: exactly one lane pulled a second one
    assert report.refills == 1
    assert verify_serial_equality(report) == []
    # determinism of the population run itself: same inputs, same bytes
    again = run_population(schedules, population=3)
    assert [r.fingerprint for r in again.results] == [
        r.fingerprint for r in report.results
    ]


def test_population_rejects_fs_backend():
    """The fs backend keeps thread-pool timing and cannot honor the
    serial-equality contract — refused loudly, not silently degraded."""
    sched = generate(0, 3, 10, FaultConfig.none(), backend="fs")
    with pytest.raises(ValueError, match="memory-backend only"):
        run_population([sched])


def test_verify_serial_equality_catches_divergence():
    """The checker itself must not be a rubber stamp: a doctored
    fingerprint or fault tally is reported, named by seed."""
    sched = generate(3, 3, 20, FaultConfig.all_faults(), members=6)
    report = run_population([sched])
    assert verify_serial_equality(report) == []
    forged = PopulationReport(
        schedules=list(report.schedules),
        results=[SimResult(None, fingerprint="f" * 64,
                           fault_stats=report.results[0].fault_stats)],
    )
    problems = verify_serial_equality(forged)
    assert len(problems) == 1 and "seed 3" in problems[0]
    forged2 = PopulationReport(
        schedules=list(report.schedules),
        results=[SimResult(None,
                           fingerprint=report.results[0].fingerprint)],
    )
    assert any("fault tallies" in p for p in verify_serial_equality(forged2))


# ------------------------------------------------------- budget mode


def test_budget_gates_starts_and_refills_lanes(monkeypatch):
    """`--budget-s` semantics on a deterministic clock: lanes start
    schedules only while the budget is open, a finished lane refills
    with the next seed, in-flight schedules always run to completion
    (the ±1-cycle contract), and the seeds drawn are contiguous from
    ``start_seed`` — no seed is ever skipped or half-run."""
    from crdt_enc_tpu.sim import population as pop_mod

    class FakeTime:
        def __init__(self, step):
            self.now, self.step = 0.0, step

        def perf_counter(self):
            self.now += self.step
            return self.now

    # calls: t0=0.25 | lane1 0.50 (ok, s0) | lane2 0.75 (ok, s1) |
    # first finisher 1.00 (ok -> REFILL s2) | 1.25, 1.50 (expired) |
    # final wall — 3 schedules, 1 refill, both lanes' last runs finish
    monkeypatch.setattr(pop_mod, "time", FakeTime(0.25))
    substrate = PopulationSubstrate()
    try:
        report = run_budget(
            lambda seed: generate(seed, 2, 5, FaultConfig.none(),
                                  members=4),
            budget_s=1.0, population=2, start_seed=10,
            substrate=substrate,
        )
    finally:
        substrate.close()
    assert [s.seed for s in report.schedules] == [10, 11, 12]
    assert report.refills == 1
    assert all(r.ok for r in report.results)
    # every started schedule produced a full result (never killed)
    assert all(r.fingerprint for r in report.results)


# -------------------------------------- explore CLI: population + shrink


def test_explore_population_cli_with_coverage_out(tmp_path, capsys):
    """`tools.sim explore --population P --coverage-out` end to end:
    exit 0, per-seed reports, and a loadable co-fire matrix counting
    exactly the swept runs."""
    cov = tmp_path / "cov.json"
    rc = sim_cli.main([
        "explore", "--seeds", "0:2", "--replicas", "2", "--steps", "25",
        "--members", "6", "--faults", "all", "--population", "2",
        "--coverage-out", str(cov),
    ])
    out = capsys.readouterr().out
    assert rc == 0
    assert "population 2" in out
    matrix = CoFireMatrix.load(str(cov))
    assert matrix.runs == 2
    # base vocabulary always on; no run enabled the extensions
    assert all(
        matrix.cells[(f, v)] == 0
        for f in FaultConfig.CLASSES
        for v in ("deltas", "daemon", "strong_reads")
    )
    assert sum(matrix.cells[(f, "base")] for f in FaultConfig.CLASSES) > 0


def test_explore_population_refuses_fs_backend():
    with pytest.raises(SystemExit, match="backend memory"):
        sim_cli.main([
            "explore", "--seeds", "0:2", "--backend", "fs",
            "--population", "2",
        ])
    with pytest.raises(SystemExit, match="backend memory"):
        sim_cli.main([
            "explore", "--seeds", "0:2", "--backend", "fs",
            "--budget-s", "1",
        ])


def test_explore_population_violation_shrinks_to_replayable_fixture(
    tmp_path, monkeypatch, capsys
):
    """Satellite: a violation found INSIDE a population still ddmin-
    shrinks to a replayable fixture.  The population stage is faked to
    report one failing schedule (a synthetic two-step oracle, the
    shrinker-test idiom); the shrink itself runs the real ddmin through
    the CLI's serial executor, and the written fixture must be minimal,
    schema-clean, and replayable by the real runner."""
    import crdt_enc_tpu.sim as sim_pkg

    base = generate(0, 3, 30, FaultConfig.all_faults())
    needles = [Step("rotate", 2), Step("compact", 2)]
    bad = base.with_steps(list(base.steps) + needles)
    violation = Violation("divergence", "synthetic", step=3)

    def fake_run_population(schedules, *, population=None, substrate=None):
        return PopulationReport(
            schedules=[generate(1, 3, 30, FaultConfig.all_faults()), bad],
            results=[SimResult(None, fingerprint="a" * 64),
                     SimResult(violation)],
        )

    def oracle(s):
        has = {(st.kind, st.replica) for st in s.steps}
        if ("rotate", 2) in has and ("compact", 2) in has:
            return SimResult(Violation("divergence", "synthetic"))
        return SimResult(None)

    monkeypatch.setattr(sim_pkg, "run_population", fake_run_population)
    monkeypatch.setattr(sim_cli, "_execute", oracle)
    out_path = tmp_path / "shrunk.json"
    rc = sim_cli.main([
        "explore", "--seeds", "0:2", "--population", "2",
        "--shrink", str(out_path),
    ])
    assert rc == 1
    assert "shrunk seed 0" in capsys.readouterr().out
    with open(out_path) as f:
        fixture = json.load(f)
    small = Schedule.from_obj(fixture)  # schema-clean
    kinds = sorted((s.kind, s.replica) for s in small.steps)
    assert kinds == [("compact", 2), ("rotate", 2)]
    assert small.faults.enabled_classes() == []
    assert fixture["violation"]["invariant"] == "divergence"
    # replayable by the REAL runner (monkeypatch bypassed), and — like
    # every committed fixture — now passing
    assert run_schedule(small).ok


# ------------------------------------------------- co-fire coverage map


def _result_firing(*classes):
    r = SimResult(None)
    for c in classes:
        r.fault_stats[c] = 3
    return r


def test_cofire_matrix_counts_holes_and_roundtrips(tmp_path):
    m = CoFireMatrix()
    m.record(generate(0, 3, 10, FaultConfig.all_faults()),
             _result_firing("torn_read"))
    m.record(generate(1, 3, 10, FaultConfig.all_faults(), deltas=True),
             _result_firing("torn_read", "write_crash"))
    assert m.runs == 2
    assert m.cells[("torn_read", "base")] == 2
    assert m.cells[("torn_read", "deltas")] == 1
    assert m.cells[("write_crash", "deltas")] == 1
    assert m.cells[("write_crash", "daemon")] == 0
    holes = m.holes()
    assert ("torn_read", "base") not in holes
    assert ("stale_checkpoint", "base") in holes
    # enabled-but-never-fired is a hole too: firing is what counts
    assert ("dup_delivery", "base") in holes

    m.dump(str(tmp_path / "cov.json"))
    again = CoFireMatrix.load(str(tmp_path / "cov.json"))
    assert again.to_obj() == m.to_obj()
    with pytest.raises(ValueError, match="version"):
        CoFireMatrix.from_obj({**m.to_obj(), "version": 99})

    table = m.render()
    assert "torn_read" in table and all(v in table for v in VOCABULARIES)
    assert "never-co-fired" in table
    full = CoFireMatrix()
    full.record(
        generate(2, 3, 10, FaultConfig.all_faults(), deltas=True,
                 daemon=True, strong_reads=True),
        _result_firing(*FaultConfig.CLASSES),
    )
    assert full.holes() == []
    assert "every fault×vocabulary pair has co-fired" in full.render()


def test_simcov_cli_renders_json_and_rejects_garbage(tmp_path, capsys):
    m = CoFireMatrix()
    m.record(generate(0, 3, 10, FaultConfig.all_faults()),
             _result_firing("torn_read"))
    path = tmp_path / "cov.json"
    m.dump(str(path))
    assert obs_report.main(["simcov", str(path)]) == 0
    assert "torn_read" in capsys.readouterr().out
    assert obs_report.main(["simcov", str(path), "--json"]) == 0
    obj = json.loads(capsys.readouterr().out)
    assert obj["runs"] == 1 and obj["cells"]["torn_read:base"] == 1
    (tmp_path / "junk.json").write_text("{nope")
    assert obs_report.main(["simcov", str(tmp_path / "junk.json")]) == 2
    assert "unreadable" in capsys.readouterr().err


# ------------------------------------------------ bench + trend pickup


def test_bench_sim_population_record_and_refusal_guard(monkeypatch, capsys):
    """Satellite: ``bench.py --sim --population P`` commits a
    ``_pP``-suffixed record only when every schedule's fingerprint
    matches its serial twin — a doctored verifier must abort the
    record, a clean run must stamp ``serial_equivalent``."""
    import bench

    monkeypatch.setenv("BENCH_LOCAL_DISABLE", "1")
    monkeypatch.setenv("BENCH_SIM_SEEDS", "2")
    monkeypatch.setattr(
        "sys.argv",
        ["bench.py", "--sim", "--replicas", "2", "--steps", "20",
         "--population", "2"],
    )
    bench.bench_sim(smoke=True)
    rec = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rec["config"] == "sim_2r_20s_all_p2"
    assert rec["population"] == 2
    assert rec["serial_equivalent"] is True
    assert rec["violations"] == 0
    assert rec["metric"] == "sim_schedules_per_sec"

    import crdt_enc_tpu.sim as sim_pkg

    monkeypatch.setattr(
        sim_pkg, "verify_serial_equality",
        lambda report: ["seed 0: population fingerprint aaaa != serial bbbb"],
    )
    with pytest.raises(SystemExit, match="refusing to record"):
        bench.bench_sim(smoke=True)


def test_population_record_rides_the_trend_gate(tmp_path):
    """The committed ``--sim --population`` record is a first-class
    trend config, SEPARATE from the serial sim series (the ``_pP``
    suffix), holds the ≥5× bar over the 0.37 serial baseline, and the
    ``--fail-on-regression`` math applies to it."""
    records = sink.read_records(str(REPO / "BENCH_LOCAL.jsonl"))
    pop_recs = [
        r for r in records
        if r.get("metric") == "sim_schedules_per_sec"
        and r.get("population", 0) > 1
    ]
    assert pop_recs, "committed BENCH_LOCAL carries no population record"
    rec = pop_recs[-1]
    assert rec["config"].endswith(f"_p{rec['population']}")
    assert rec["serial_equivalent"] is True
    assert rec["violations"] == 0
    assert rec["replicas"] >= 8 and rec["steps"] >= 250
    assert rec["value"] >= 5 * 0.37  # the ISSUE-18 acceptance bar

    trend = fleet.bench_trend(records, metric="sim_schedules_per_sec")
    pop_cfgs = [
        c for c in trend if "_p" in c["shape"].get("config", "")
    ]
    serial_cfgs = [
        c for c in trend if c["shape"].get("config") == "sim_8r_250s_all"
    ]
    assert pop_cfgs, "population config collapsed into the serial series"
    assert serial_cfgs, "serial baseline series disappeared"
    # the regression gate picks the new series up like any other
    regressed = dict(rec, value=rec["value"] / 10)
    t2 = fleet.bench_trend(
        records + [regressed], metric="sim_schedules_per_sec"
    )
    assert any(
        "_p" in c["shape"].get("config", "")
        for c in fleet.trend_regressions(t2, 45)
    )


# --------------------------------------------- obs: taps + attribution


def test_counter_tap_is_context_local_and_nests():
    trace.add("tap_probe_total", 0)
    with trace.counter_tap() as outer:
        trace.add("tap_probe_total", 2)
        with trace.counter_tap() as inner:
            trace.add("tap_probe_total", 5)
        trace.add("tap_probe_total", 1)
    # inner sees only its window; outer sees everything in its window
    assert inner == {"tap_probe_total": 5}
    assert outer == {"tap_probe_total": 8}
    trace.add("tap_probe_total", 100)
    assert outer == {"tap_probe_total": 8}  # closed taps are closed

    async def scenario():
        with trace.counter_tap() as tap:
            async def child():
                trace.add("tap_probe_total", 3)
            # tasks and to_thread copy the context at creation: a lane's
            # whole task tree lands in the lane's tap
            await asyncio.gather(child(), asyncio.create_task(child()))
            await asyncio.to_thread(trace.add, "tap_probe_total", 4)
        return tap

    tap = asyncio.run(scenario())
    assert tap == {"tap_probe_total": 10}

    # a PLAIN thread does not inherit the context — and must not leak
    # its increments into a tap it was never inside
    with trace.counter_tap() as tap2:
        t = threading.Thread(target=trace.add, args=("tap_probe_total", 7))
        t.start()
        t.join()
    assert tap2 == {}


def test_attribution_ignores_sim_spans():
    """Sim harness spans wrap the serve spans a sim service cycle
    records; attribution must drop them or a whole simulation reads as
    one impossibly slow cycle."""
    snap = {
        "spans": {
            "sim.population": {"count": 1, "seconds": 500.0},
            "sim.run": {"count": 4, "seconds": 480.0},
            "serve.cycle": {"count": 1, "seconds": 2.0},
            "serve.fold": {"count": 1, "seconds": 0.5},
        },
        "counters": {}, "gauges": {},
    }
    rep = attribution.attribute_cycle(snap, ops=100)
    assert rep["pipeline"] == "serve"
    assert rep["wall_s"] == 2.0  # serve.cycle, not the sim envelope
    for stage in rep["stages"].values():
        assert not any(n.startswith("sim.") for n in stage["spans"])

    def ev(name, t0, t1):
        return {"name": name, "kind": "span", "t0": t0, "t1": t1,
                "meta": None, "tid": 1, "thread": "t"}

    rep2 = attribution.attribute_cycle(
        {"spans": {"serve.fold": {"count": 1, "seconds": 0.5}},
         "counters": {}, "gauges": {}},
        pipeline="serve",
        events=[ev("sim.run", 0.0, 500.0), ev("serve.fold", 1.0, 1.5)],
    )
    assert rep2["wall_s"] == 0.5  # event extent excludes sim.* too


# --------------------------------------- shared service + compile classes


def test_run_cycle_shared_queues_concurrent_owners():
    """Two owners driving one FoldService concurrently must queue and
    both seal — where bare ``run_cycle`` refuses reentrancy — and the
    lock survives a second event loop (a service outliving one
    ``asyncio.run``)."""
    from crdt_enc_tpu.backends import (
        IdentityCryptor, MemoryRemote, MemoryStorage, PlainKeyCryptor,
    )
    from crdt_enc_tpu.core import Core, OpenOptions, orset_adapter
    from crdt_enc_tpu.parallel import TpuAccelerator
    from crdt_enc_tpu.serve import FoldService
    from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

    def opts(storage):
        return OpenOptions(
            storage=storage, cryptor=IdentityCryptor(),
            key_cryptor=PlainKeyCryptor(), adapter=orset_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1, create=True,
            accelerator=TpuAccelerator(min_device_batch=1),
        )

    async def build_core(tag):
        core = await Core.open(opts(MemoryStorage(MemoryRemote())))
        for i in range(8):
            await core.apply_ops([core.with_state(
                lambda s, m=b"%s-%d" % (tag, i): s.add_ctx(core.actor_id, m)
            )])
        return core

    service = FoldService([])

    async def first_loop():
        a, b = await build_core(b"a"), await build_core(b"b")
        ra, rb = await asyncio.gather(
            service.run_cycle_shared([a]), service.run_cycle_shared([b]),
        )
        assert ra[0].error is None and ra[0].sealed
        assert rb[0].error is None and rb[0].sealed

    async def second_loop():
        c = await build_core(b"c")
        (rc,) = await service.run_cycle_shared([c])
        assert rc.error is None and rc.sealed

    asyncio.run(first_loop())
    asyncio.run(second_loop())  # per-loop lock rebuild, not a crash
    service.close()


def test_population_compiles_constant_as_p_grows():
    """The throughput mechanism itself: after a 2-schedule warmup, a
    LARGER population of fresh seeds through the SAME substrate must
    not add steady-state XLA compiles — the bucketed compile classes
    are fleet properties, not schedule properties."""
    obs_runtime.track_recompiles()
    substrate = PopulationSubstrate()
    try:
        warm = [generate(s, 3, 30, FaultConfig.all_faults(), members=6)
                for s in range(4)]
        report = run_population(warm, substrate=substrate)
        assert all(r.ok for r in report.results)
        baseline = obs_runtime.recompile_count()
        # the exact half of the property: the same shapes through the
        # same substrate compile NOTHING — P lanes share one program set
        again = run_population(warm, population=4, substrate=substrate)
        assert all(r.ok for r in again.results)
        assert obs_runtime.recompile_count() == baseline, (
            "re-running warmed schedules recompiled — the shared "
            "substrate's program cache leaked per-lane state"
        )
        # the asymptotic half: TWICE as many fresh seeds may only touch
        # the occasional unwarmed bucket class (strictly sub-linear),
        # never one-compile-set-per-schedule
        more = [generate(s, 3, 30, FaultConfig.all_faults(), members=6)
                for s in range(10, 18)]
        report2 = run_population(more, population=4, substrate=substrate)
        assert all(r.ok for r in report2.results)
        grown = obs_runtime.recompile_count() - baseline
        assert grown <= len(more) // 2, (
            f"{len(more)} fresh schedules recompiled {grown} programs — "
            "the shared substrate's compile classes leaked schedule shape"
        )
    finally:
        substrate.close()


# ------------------------------------------------------ fleet acceptance


@pytest.mark.slow
def test_population_acceptance_32_schedules():
    """ISSUE-18 acceptance: a 32-schedule all-vocabulary population
    through one substrate — zero violations, every fault class fires
    somewhere in the population, and a serial-equality spot check on
    the first four schedules upholds the law at scale."""
    schedules = [
        generate(seed, 4, 100, FaultConfig.all_faults(), members=6,
                 deltas=True, daemon=True, strong_reads=True)
        for seed in range(32)
    ]
    report = run_population(schedules, population=8)
    assert report.violations == []
    assert report.refills == 32 - 8
    fired = set()
    for r in report.results:
        fired.update(k for k, v in r.fault_stats.items() if v)
    assert fired == set(FaultConfig.CLASSES)
    sample = PopulationReport(
        schedules=report.schedules[:4], results=report.results[:4],
    )
    assert verify_serial_equality(sample) == []

"""Bulk (fold_payloads) front ends for the rest of the CRDT catalogue —
GSet, LWWReg, MVReg, SeqList, MerkleReg — must equal per-op apply
(round-3 item: every catalogue type accepted by the bulk surface).

The LWWReg and MVReg paths route through the device kernels
(``lww_fold`` at K=1, ``mvreg_dominance_keep``); GSet/SeqList/MerkleReg
are host folds by design (docs/PARITY.md row 14 documents why no device
kernel exists for them)."""

from __future__ import annotations

import random
import uuid

import pytest

from crdt_enc_tpu.models import (
    GSet, LWWReg, MVReg, MerkleReg, SeqList, canonical_bytes,
)
from crdt_enc_tpu.parallel.accel import TpuAccelerator
from crdt_enc_tpu.utils import codec

ACTORS = [uuid.UUID(int=i + 1).bytes for i in range(4)]


def _seal(op_objs, per_file=5):
    return [
        codec.pack(op_objs[i : i + per_file])
        for i in range(0, len(op_objs), per_file)
    ]


def _check(proto_cls, ops_to_obj, make_ops, accel, seed=0, **proto_kw):
    rng = random.Random(seed)
    ops = make_ops(rng)
    objs = [ops_to_obj(op) for op in ops]
    ref = proto_cls(**proto_kw)
    for op in ops:
        ref.apply(op)
    bulk = proto_cls(**proto_kw)
    ok = accel.fold_payloads(bulk, _seal(objs))
    assert ok, "bulk path declined"
    assert canonical_bytes(bulk) == canonical_bytes(ref)


@pytest.mark.parametrize("seed", range(5))
def test_gset_bulk(seed):
    def make(rng):
        return [rng.randrange(20) for _ in range(rng.randrange(0, 60))]

    _check(GSet, lambda op: op, make, TpuAccelerator(), seed)


@pytest.mark.parametrize("min_batch", [1, 10**6])  # device and host routes
@pytest.mark.parametrize("seed", range(5))
def test_lwwreg_bulk(seed, min_batch):
    def make(rng):
        return [
            LWWReg().write(
                rng.randrange(100), rng.choice(ACTORS), rng.randrange(5)
            )
            for _ in range(rng.randrange(1, 50))
        ]

    _check(
        LWWReg, lambda op: op.to_obj(), make,
        TpuAccelerator(min_device_batch=min_batch), seed,
    )


@pytest.mark.parametrize("min_batch", [1, 10**6])
@pytest.mark.parametrize("seed", range(5))
def test_mvreg_bulk(seed, min_batch):
    def make(rng):
        # concurrent writers with partially-ordered clocks: each actor
        # writes from its own (occasionally synced) view
        reg_views = [MVReg() for _ in ACTORS]
        ops = []
        for _ in range(rng.randrange(1, 40)):
            i = rng.randrange(len(ACTORS))
            op = reg_views[i].write_ctx(ACTORS[i], rng.randrange(10))
            ops.append(op)
            reg_views[i].apply(op)
            if rng.random() < 0.3:  # occasionally sync another view
                j = rng.randrange(len(ACTORS))
                reg_views[j].merge(reg_views[i])
        return ops

    _check(
        MVReg, lambda op: [op.clock.to_obj(), op.value], make,
        TpuAccelerator(min_device_batch=min_batch), seed,
    )


@pytest.mark.parametrize("seed", range(5))
def test_seqlist_bulk(seed):
    def make(rng):
        view = SeqList()
        ops = []
        for _ in range(rng.randrange(1, 40)):
            if view.read() and rng.random() < 0.3:
                op = view.delete_ctx(rng.randrange(len(view.read())))
            else:
                op = view.insert_ctx(
                    rng.choice(ACTORS),
                    rng.randrange(len(view.read()) + 1),
                    rng.randrange(100),
                )
            ops.append(op)
            view.apply(op)
        return ops

    _check(SeqList, lambda op: op.to_obj(), make, TpuAccelerator(), seed)


@pytest.mark.parametrize("seed", range(5))
def test_merklereg_bulk(seed):
    def make(rng):
        view = MerkleReg()
        ops = []
        for _ in range(rng.randrange(1, 30)):
            op = view.write_ctx(rng.randrange(50))
            ops.append(op)
            view.apply(op)
        return ops

    _check(
        MerkleReg, lambda op: op.to_obj(), make, TpuAccelerator(), seed
    )


def test_lwwreg_bulk_into_populated_state():
    accel = TpuAccelerator(min_device_batch=1)
    ref = LWWReg()
    bulk = LWWReg()
    first = LWWReg().write(50, ACTORS[0], "existing")
    ref.apply(first)
    bulk.apply(first)
    ops = [LWWReg().write(ts, ACTORS[1], f"v{ts}") for ts in (10, 60, 40)]
    for op in ops:
        ref.apply(op)
    assert accel.fold_payloads(bulk, _seal([o.to_obj() for o in ops]))
    assert canonical_bytes(bulk) == canonical_bytes(ref)
    # stale batch: populated slot must survive
    ops2 = [LWWReg().write(5, ACTORS[2], "old")]
    ref2, bulk2 = LWWReg(), LWWReg()
    ref2.apply(first), bulk2.apply(first)
    for op in ops2:
        ref2.apply(op)
    assert accel.fold_payloads(bulk2, _seal([o.to_obj() for o in ops2]))
    assert canonical_bytes(bulk2) == canonical_bytes(ref2)


def test_mvreg_bulk_into_populated_state():
    accel = TpuAccelerator(min_device_batch=1)
    base = MVReg()
    w = base.write_ctx(ACTORS[0], "a")
    ref = MVReg()
    ref.apply(w)
    bulk = MVReg()
    bulk.apply(w)
    # a dominating write and an unrelated concurrent one
    op2 = ref.write_ctx(ACTORS[1], "b")
    solo = MVReg()
    op3 = solo.write_ctx(ACTORS[2], "c")
    for op in (op2, op3):
        ref.apply(op)
    objs = [[op.clock.to_obj(), op.value] for op in (op2, op3)]
    assert accel.fold_payloads(bulk, _seal(objs))
    assert canonical_bytes(bulk) == canonical_bytes(ref)

"""Test configuration: force an 8-device virtual CPU mesh before JAX imports.

Benchmarks (bench.py) run on the real TPU in a separate process; tests
exercise sharding/collectives on virtual CPU devices so they run anywhere.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

"""Test configuration: force an 8-device virtual CPU mesh.

Benchmarks (bench.py) run on the real TPU in a separate process; tests
exercise sharding/collectives on virtual CPU devices so they run anywhere —
including when the TPU tunnel is unavailable.

Environment quirk: a sitecustomize hook imports jax eagerly in every
interpreter and registers the axon TPU PJRT plugin, so mutating
JAX_PLATFORMS here is too late — the config must be updated through the
already-imported jax.  Backend *initialization* is still lazy, so forcing
the platform list to "cpu" before any test touches a device keeps the (possibly
unreachable) TPU tunnel entirely out of the test run.
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PJRT_LIBRARY_PATH", None)
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

if "jax" in sys.modules:
    import jax

    jax.config.update("jax_platforms", "cpu")
    assert not jax._src.xla_bridge._backends, (
        "a backend initialized before conftest could force cpu; "
        "tests would touch the TPU tunnel"
    )


# ---- collection bookkeeping for the PARITY.md test-count assertion ----
# (tests/test_parity_count.py): the documented count kept drifting from
# the real one (VERDICT r4 weak item 5), so it is now asserted in CI.
# The dict is stashed on the pytest config (pytest_configure below) and
# read through the ``request`` fixture — never imported from here, so the
# suite survives --import-mode=importlib / src-layout changes where
# ``import conftest`` does not resolve (ADVICE r5, low).
COLLECT_INFO = {"n_items": None, "n_files": None, "n_deselected": 0}


def pytest_configure(config):
    config.crdt_collect_info = COLLECT_INFO


def pytest_deselected(items):
    # -k / -m / --deselect runs must not trip the count assertion
    COLLECT_INFO["n_deselected"] += len(items)


def pytest_collection_finish(session):
    files = {item.location[0] for item in session.items}
    COLLECT_INFO["n_items"] = len(session.items)
    COLLECT_INFO["n_files"] = len(files)

"""The C++ bulk op decoder must agree exactly with the Python columnar
flattening over the canonical op encodings."""

import ctypes
import uuid

import numpy as np

from crdt_enc_tpu import native
from crdt_enc_tpu import ops as K
from crdt_enc_tpu.models import ORSet, PNCounter
from crdt_enc_tpu.utils import codec

ACTORS = [uuid.UUID(int=i + 1).bytes for i in range(4)]


def decode_orset_native(payload: bytes, actors_sorted: list[bytes]):
    lib = native.load()
    bp, _b = native.in_ptr(payload)
    n_rows = lib.orset_count_rows(bp, len(payload))
    assert n_rows >= 0, "malformed payload"
    actors_flat = b"".join(actors_sorted)
    ap, _a = native.in_ptr(actors_flat)
    kind = np.zeros(max(n_rows, 1), np.int8)
    moff = np.zeros(max(n_rows, 1), np.uint64)
    mlen = np.zeros(max(n_rows, 1), np.uint64)
    actor = np.zeros(max(n_rows, 1), np.int32)
    counter = np.zeros(max(n_rows, 1), np.int32)
    rows = lib.orset_decode(
        bp,
        len(payload),
        ap,
        len(actors_sorted),
        kind.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        moff.ctypes.data_as(native.u64p),
        mlen.ctypes.data_as(native.u64p),
        actor.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        counter.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    assert rows == n_rows
    members = [
        payload[int(moff[i]) : int(moff[i]) + int(mlen[i])] for i in range(rows)
    ]
    return kind[:rows], members, actor[:rows], counter[:rows]


def test_orset_decode_matches_python():
    state = ORSet()
    ops = []
    for i in range(40):
        a = ACTORS[i % 4]
        if i % 5 == 4:
            op = state.rm_ctx(i % 3)
            if op.ctx.is_empty():
                continue
        else:
            op = state.add_ctx(a, i % 3)
        state.apply(op)
        ops.append(op)
    payload = codec.pack([op.to_obj() for op in ops])

    actors_sorted = sorted(ACTORS)
    kind, members_raw, actor_ix, counter = decode_orset_native(
        payload, actors_sorted
    )

    # python reference flattening
    cols = K.orset_ops_to_columns(ops)
    assert list(kind) == list(cols.kind)
    assert list(counter) == list(cols.counter)
    # native actor indices are into the sorted table
    py_actors = [cols.replicas.items[i] for i in cols.actor]
    nat_actors = [actors_sorted[i] for i in actor_ix]
    assert py_actors == nat_actors
    # native members are msgpack spans; decode and compare
    py_members = [cols.members.items[i] for i in cols.member]
    nat_members = [codec.unpack(m) for m in members_raw]
    assert py_members == nat_members


def test_orset_decode_rejects_malformed():
    lib = native.load()
    bad = codec.pack([[7, b"x", [b"a" * 16, 1]]])  # kind 7 does not exist
    bp, _b = native.in_ptr(bad)
    assert lib.orset_count_rows(bp, len(bad)) == -1
    trunc = codec.pack([[0, b"x", [b"a" * 16, 1]]])[:-3]
    tp, _t = native.in_ptr(trunc)
    assert lib.orset_count_rows(tp, len(trunc)) == -1


def test_orset_decode_truncated_counter_uint16_member():
    # Fast-path OOB regression (round-4 review): a payload whose last op
    # has a uint16 member id and is truncated right before the counter
    # passes the fast path's 24-byte entry guard but leaves the counter
    # byte exactly at the buffer end — the decoder must decline, not
    # read past it.
    from crdt_enc_tpu.ops.native_decode import decode_orset_payload_spans

    actors = [b"a" * 16]
    # 0x91 (array-1) + 93 00 cd XXXX 92 c4 10 <16B actor>, counter missing
    payload = bytes(
        [0x91, 0x93, 0x00, 0xCD, 0x01, 0x00, 0x92, 0xC4, 0x10]
    ) + actors[0]
    assert len(payload) - 1 == 24  # exactly the fast-path entry guard
    assert decode_orset_payload_spans([payload], actors) is None


def test_orset_decode_random_bytes_never_crash():
    # the decoder (incl. the add fast path) must decline garbage cleanly:
    # random buffers and randomly truncated valid payloads — never a
    # crash or wild read (run under the normal allocator; the bound
    # checks themselves are what this exercises)
    import numpy as np

    from crdt_enc_tpu.ops.native_decode import decode_orset_payload_spans

    rng = np.random.default_rng(0)
    actors = [b"a" * 16, b"b" * 16]
    valid = codec.pack(
        [[0, 5, [actors[0], 9]], [1, 6, {actors[1]: 2}]] * 10
    )
    for trial in range(300):
        if trial % 2:
            buf = rng.bytes(int(rng.integers(0, 120)))
        else:
            cut = int(rng.integers(0, len(valid)))
            buf = valid[:cut] + rng.bytes(int(rng.integers(0, 8)))
        out = decode_orset_payload_spans([buf], actors)
        assert out is None or len(out) == 6  # decline or decode, no crash


def test_counter_decode_matches_python():
    state = PNCounter()
    ops = []
    for i in range(30):
        a = ACTORS[i % 4]
        op = state.inc(a, i % 3 + 1) if i % 2 else state.dec(a, 1)
        state.apply(op)
        ops.append(op)
    from crdt_enc_tpu.core.adapters import pncounter_adapter

    adapter = pncounter_adapter()
    payload = codec.pack([adapter.op_to_obj(op) for op in ops])

    lib = native.load()
    actors_sorted = sorted(ACTORS)
    bp, _b = native.in_ptr(payload)
    ap, _a = native.in_ptr(b"".join(actors_sorted))
    n = len(ops)
    sign = np.zeros(n, np.int8)
    actor = np.zeros(n, np.int32)
    counter = np.zeros(n, np.int32)
    rows = lib.counter_decode(
        bp,
        len(payload),
        ap,
        len(actors_sorted),
        sign.ctypes.data_as(ctypes.POINTER(ctypes.c_int8)),
        actor.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        counter.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
    )
    assert rows == n
    cols = K.counter_ops_to_columns(ops)
    assert list(sign) == list(cols.sign)
    assert list(counter) == list(cols.counter)
    assert [cols.replicas.items[i] for i in cols.actor] == [
        actors_sorted[i] for i in actor
    ]

"""``hypothesis`` exports, or skip-stubs when the wheel is absent.

Import as ``from _hyp import given, settings, st`` — on boxes without the
hypothesis wheel the property tests then SKIP individually instead of
taking the whole file down as a collection error (which also hid every
deterministic test sharing the file).  ``HAVE_HYPOTHESIS`` lets a test
assert on the real thing when it matters.
"""

try:
    from hypothesis import given, settings, strategies as st  # noqa: F401

    HAVE_HYPOTHESIS = True
except ModuleNotFoundError:
    import inspect

    import pytest

    HAVE_HYPOTHESIS = False

    def given(*_a, **_k):
        def deco(fn):
            # the replacement must serve two call paths: collected as a
            # test (the skip MARK pre-empts setup, so strategy-shaped
            # params are never looked up as fixtures) and called directly
            # from inside another test (the body raises Skipped).  The
            # forwarded __signature__ keeps stacked decorators like an
            # outer @pytest.mark.parametrize resolving their argnames.
            def skipped(*_args, **_kwargs):
                pytest.skip("hypothesis not installed")

            skipped.__name__ = fn.__name__
            skipped.__doc__ = fn.__doc__
            skipped.__signature__ = inspect.signature(fn)
            return pytest.mark.skip(reason="hypothesis not installed")(
                skipped
            )

        return deco

    def settings(*_a, **_k):
        return lambda fn: fn

    class _Strategies:
        """Opaque strategy factory: builders are only ever passed back to
        ``given``, which the stub ignores wholesale."""

        def __getattr__(self, name):
            return lambda *a, **k: None

    st = _Strategies()

"""Parity: the Pallas sorted one-hot-matmul fold (ops/pallas_fold.py)
must be value-identical to the XLA scatter fold (ops/orset.py) — which
tests/test_ops_kernels.py already pins byte-identical to the host
reference — on every shape/regime the router can hand it.

Runs in Pallas interpreter mode on the CPU test platform; the real-MXU
path is exercised by bench.py on TPU with the same byte-equality check.
"""

from __future__ import annotations

import numpy as np
import pytest

from crdt_enc_tpu import ops as K
from crdt_enc_tpu.ops.pallas_fold import MAX_COUNTER, fold_cap, orset_fold_pallas


def _gen(N, E, R, seed, max_counter=200, rm_frac=0.3, pad_frac=0.05):
    rng = np.random.default_rng(seed)
    kind = (rng.random(N) < rm_frac).astype(np.int8)
    member = rng.integers(0, E, N, dtype=np.int32)
    actor = rng.integers(0, R, N, dtype=np.int32)
    pad = rng.random(N) < pad_frac
    actor = np.where(pad, R, actor)
    counter = rng.integers(1, max_counter, N, dtype=np.int32)
    return kind, member, actor, counter


def _run_both(clock0, add0, rm0, kind, member, actor, counter, E, R,
              layouts=("ablk", "wide"), **kw):
    ref = K.orset_fold(
        clock0, add0, rm0, kind, member, actor, counter,
        num_members=E, num_replicas=R,
        retire_rm=kw.get("retire_rm", True),
    )
    for layout in layouts:
        got = orset_fold_pallas(
            clock0, add0, rm0, kind, member, actor, counter,
            num_members=E, num_replicas=R, tile_cap=fold_cap(member, E),
            interpret=True, layout=layout, **kw,
        )
        for r, g, name in zip(ref, got, ("clock", "add", "rm")):
            np.testing.assert_array_equal(
                np.asarray(r), np.asarray(g), err_msg=f"{layout}:{name}"
            )


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize(
    "N,E,R",
    [
        (256, 16, 20),  # H=1, small
        (512, 8, 300),  # H=3, one tile
        (777, 40, 130), # odd sizes, E not tile-aligned via Ep pad
        (64, 3, 5),     # tiny
    ],
)
def test_parity_random(N, E, R, seed):
    rng = np.random.default_rng(seed + 100)
    clock0 = rng.integers(0, 50, R).astype(np.int32)
    add0 = np.zeros((E, R), np.int32)
    rm0 = np.zeros((E, R), np.int32)
    # a plausible starting state: some live dots above some horizons
    add0[rng.random((E, R)) < 0.1] = 40
    rm0[rng.random((E, R)) < 0.05] = 30
    add0 = np.where(add0 > rm0, add0, 0)
    rm0 = np.where(rm0 > clock0[None, :], rm0, 0)
    kind, member, actor, counter = _gen(N, E, R, seed)
    _run_both(clock0, add0, rm0, kind, member, actor, counter, E, R)


def test_parity_unretired_and_empty():
    E, R = 16, 40
    clock0 = np.zeros(R, np.int32)
    z = np.zeros((E, R), np.int32)
    kind, member, actor, counter = _gen(300, E, R, 9)
    _run_both(clock0, z, z, kind, member, actor, counter, E, R,
              retire_rm=False)
    # all-padding batch: nothing changes
    actor_all_pad = np.full(128, R, np.int32)
    _run_both(
        clock0, z, z, np.zeros(128, np.int8), np.zeros(128, np.int32),
        actor_all_pad, np.ones(128, np.int32), E, R,
    )


def test_parity_skewed_tile():
    # every op on one member: a single tile holds the whole batch (cap
    # grows to cover it) while other tiles are empty
    E, R = 32, 64
    N = 600
    rng = np.random.default_rng(3)
    kind = (rng.random(N) < 0.2).astype(np.int8)
    member = np.full(N, 17, np.int32)
    actor = rng.integers(0, R, N, dtype=np.int32)
    counter = rng.integers(1, 1000, N, dtype=np.int32)
    clock0 = np.zeros(R, np.int32)
    z = np.zeros((E, R), np.int32)
    _run_both(clock0, z, z, kind, member, actor, counter, E, R)


def test_parity_max_counter_boundary():
    E, R = 8, 16
    N = 128
    rng = np.random.default_rng(5)
    kind = (rng.random(N) < 0.3).astype(np.int8)
    member = rng.integers(0, E, N, dtype=np.int32)
    actor = rng.integers(0, R, N, dtype=np.int32)
    counter = np.full(N, MAX_COUNTER - 1, np.int32)
    counter[: N // 2] = rng.integers(1, MAX_COUNTER, N // 2)
    clock0 = np.zeros(R, np.int32)
    z = np.zeros((E, R), np.int32)
    _run_both(clock0, z, z, kind, member, actor, counter, E, R)




def test_parity_exact_blk_multiple_with_empty_trailing_tile():
    # N an exact BLK multiple with the last tiles empty: the hi-window
    # block index of an empty trailing tile would point one past the
    # padded array without the clamp (review finding, round 3)
    from crdt_enc_tpu.ops.pallas_fold import SUB

    E, R = 16, 8
    N = SUB  # == BLK exactly (fold_cap floor), the clamp's trigger shape
    rng = np.random.default_rng(12)
    kind = (rng.random(N) < 0.2).astype(np.int8)
    member = rng.integers(0, 8, N, dtype=np.int32)  # tiles 1.. empty
    actor = rng.integers(0, R, N, dtype=np.int32)
    counter = rng.integers(1, 300, N, dtype=np.int32)
    clock0 = np.zeros(R, np.int32)
    z = np.zeros((E, R), np.int32)
    _run_both(clock0, z, z, kind, member, actor, counter, E, R)


@pytest.mark.parametrize(
    "R",
    [
        1200,   # H=10 → H_BLK=16, Hp=16, A_BLK=1 (padded hi rows)
        2500,   # H=20 → Hp=32, A_BLK=2: multi actor-block segments
        10000,  # H=79 → Hp=80, A_BLK=5: the north-star bench geometry
    ],
)
def test_parity_large_R_actor_blocks(R):
    # the ablk layout's actor-hi blocking only engages above R=1024
    # (H_BLK=16) and splits into multiple blocks above R=2048 — regimes
    # the small parity shapes never reach
    E, N = 24, 900
    rng = np.random.default_rng(21)
    kind = (rng.random(N) < 0.25).astype(np.int8)
    member = rng.integers(0, E, N, dtype=np.int32)
    actor = rng.integers(0, R, N, dtype=np.int32)
    counter = rng.integers(1, 500, N, dtype=np.int32)
    clock0 = rng.integers(0, 40, R).astype(np.int32)
    z = np.zeros((E, R), np.int32)
    _run_both(clock0, z, z, kind, member, actor, counter, E, R)


# ---- property sweep ------------------------------------------------------

from _hyp import given, settings, st  # hypothesis, or skip-stubs


@settings(max_examples=40, deadline=None)
@given(
    seed=st.integers(0, 2**16),
    n=st.integers(1, 400),
    e=st.integers(1, 48),
    r=st.integers(1, 200),
    rm_frac=st.floats(0.0, 1.0),
    clocked=st.booleans(),
)
def test_parity_hypothesis(seed, n, e, r, rm_frac, clocked):
    """Random shapes, skews, remove ratios, and starting clocks: the
    Pallas fold must equal the XLA scatter fold everywhere."""
    rng = np.random.default_rng(seed)
    kind, member, actor, counter = _gen(
        n, e, r, seed, max_counter=min(MAX_COUNTER, 400), rm_frac=rm_frac
    )
    clock0 = (
        rng.integers(0, 60, r).astype(np.int32)
        if clocked else np.zeros(r, np.int32)
    )
    add0 = np.zeros((e, r), np.int32)
    rm0 = np.zeros((e, r), np.int32)
    if clocked:
        add0[rng.random((e, r)) < 0.08] = 50
        rm0[rng.random((e, r)) < 0.04] = 35
        add0 = np.where(add0 > rm0, add0, 0)
        rm0 = np.where(rm0 > clock0[None, :], rm0, 0)
    _run_both(clock0, add0, rm0, kind, member, actor, counter, e, r)


# ---- kernel-body variants (round 4 phase-profile knobs) ------------------


@pytest.mark.parametrize("hi_mode", ["fused", "cond"])
@pytest.mark.parametrize("win_mode", ["select", "cond"])
def test_parity_kernel_body_modes(hi_mode, win_mode):
    """The branchless kernel-body variants (hi_mode="fused": one
    stacked-B matmul instead of the data-dependent hi-limb cond;
    win_mode="select": dual-load + vector select instead of the window
    cond) must be byte-identical to the default body on a shape that
    crosses the 128 limb boundary and straddles windows."""
    E, R, N = 40, 2500, 3000  # multi actor-block + straddling chunks
    rng = np.random.default_rng(7)
    kind = (rng.random(N) < 0.3).astype(np.int8)
    member = rng.integers(0, E, N, dtype=np.int32)
    actor = rng.integers(0, R, N, dtype=np.int32)
    counter = rng.integers(1, 600, N, dtype=np.int32)  # crosses 128
    clock0 = rng.integers(0, 80, R).astype(np.int32)
    z = np.zeros((E, R), np.int32)
    _run_both(
        clock0, z, z, kind, member, actor, counter, E, R,
        layouts=("ablk",), hi_mode=hi_mode, win_mode=win_mode,
    )


def test_parity_hi_skip_small_counters():
    """hi_mode="skip" (static all-counters-<128 promise) matches the
    reference when the promise holds."""
    E, R, N = 32, 300, 2000
    rng = np.random.default_rng(11)
    kind = (rng.random(N) < 0.3).astype(np.int8)
    member = rng.integers(0, E, N, dtype=np.int32)
    actor = rng.integers(0, R, N, dtype=np.int32)
    counter = rng.integers(1, 128, N, dtype=np.int32)
    clock0 = rng.integers(0, 30, R).astype(np.int32)
    z = np.zeros((E, R), np.int32)
    _run_both(
        clock0, z, z, kind, member, actor, counter, E, R,
        layouts=("ablk",), hi_mode="skip",
    )


def test_parity_blocked_accumulator():
    """acc_mode="blocked" (one contiguous add per chunk + XLA transpose
    decode) must match the member-major accumulator on a multi-block
    shape and on the A_BLK==1 degenerate."""
    from crdt_enc_tpu.ops.pallas_fold import orset_scatter_pallas

    rng = np.random.default_rng(23)
    for E, R in ((40, 2600), (16, 200)):
        N = 3000
        kind = (rng.random(N) < 0.3).astype(np.int8)
        member = rng.integers(0, E, N, dtype=np.int32)
        actor = rng.integers(0, R, N, dtype=np.int32)
        counter = rng.integers(1, 700, N, dtype=np.int32)
        cap = fold_cap(member, E)
        kw = dict(num_members=E, num_replicas=R, tile_cap=cap,
                  interpret=True)
        a = orset_scatter_pallas(kind, member, actor, counter, **kw)
        b = orset_scatter_pallas(kind, member, actor, counter,
                                 acc_mode="blocked", **kw)
        for x, y in zip(a, b):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


@settings(max_examples=30, deadline=None)
@given(
    seed=st.integers(0, 2**16),
    n=st.integers(1, 600),
    e=st.integers(1, 24),
    r=st.integers(1, 120),
    rm_frac=st.floats(0.0, 1.0),
)
def test_parity_kernel_dedup_hypothesis(seed, n, e, r, rm_frac):
    """dedup_mode="kernel" (key-only sort + in-kernel segmented run-max
    with telescoping cross-chunk emission) must equal the sorted-dedup
    scatter everywhere — small (E, R) shapes force key runs that span
    many SUBK chunks, the hard case for the carry."""
    from crdt_enc_tpu.ops.pallas_fold import orset_scatter_pallas

    kind, member, actor, counter = _gen(
        n, e, r, seed, max_counter=min(MAX_COUNTER, 500), rm_frac=rm_frac
    )
    cap = fold_cap(member, e)
    kw = dict(num_members=e, num_replicas=r, tile_cap=cap, interpret=True)
    a = orset_scatter_pallas(kind, member, actor, counter, **kw)
    b = orset_scatter_pallas(
        kind, member, actor, counter, dedup_mode="kernel", **kw
    )
    for x, y, nm in zip(a, b, ("add", "rm")):
        np.testing.assert_array_equal(
            np.asarray(x), np.asarray(y), err_msg=nm
        )


# ---- round 5: the fused-tail fold (normalize tail in the kernel epilogue)


def _well_formed_state(E, R, seed):
    """A state every real fold output satisfies: add>rm-or-0, rm retired."""
    rng = np.random.default_rng(seed)
    clock0 = rng.integers(0, 50, R).astype(np.int32)
    add0 = np.zeros((E, R), np.int32)
    rm0 = np.zeros((E, R), np.int32)
    add0[rng.random((E, R)) < 0.1] = 40
    rm0[rng.random((E, R)) < 0.05] = 60
    add0 = np.where(add0 > rm0, add0, 0)
    rm0 = np.where(rm0 > clock0[None, :], rm0, 0)
    return clock0, add0, rm0


@pytest.mark.parametrize("h_blk", [None, 32, 80])
@pytest.mark.parametrize("E,R,N", [(16, 300, 4000), (8, 2100, 3000),
                                   (40, 130, 2500)])
def test_fused_chain_parity(E, R, N, h_blk):
    """Two chained fused folds (eager AND deferred+finalize) must match
    the unfused chain byte-for-byte, across h_blk geometries."""
    from crdt_enc_tpu.ops.pallas_fold import (
        orset_fold_pallas_fused, orset_pad_state, orset_retire,
        orset_unpad_state,
    )

    st = _well_formed_state(E, R, 7)
    b1 = _gen(N, E, R, 1, max_counter=250)
    b2 = _gen(N, E, R, 2, max_counter=250)
    cap = 1 << 13
    e1 = orset_fold_pallas(*st, *b1, num_members=E, num_replicas=R,
                           tile_cap=cap, interpret=True)
    e2 = orset_fold_pallas(*e1, *b2, num_members=E, num_replicas=R,
                           tile_cap=cap, interpret=True)
    p = orset_pad_state(*st, num_members=E, num_replicas=R, h_blk=h_blk)
    # eager fused chain
    f1 = orset_fold_pallas_fused(*p, *b1, num_members=E, num_replicas=R,
                                 tile_cap=cap, interpret=True, h_blk=h_blk)
    f2 = orset_fold_pallas_fused(*f1, *b2, num_members=E, num_replicas=R,
                                 tile_cap=cap, interpret=True, h_blk=h_blk)
    got = orset_unpad_state(*f2, num_members=E, num_replicas=R)
    for r, g, name in zip(e2, got, ("clock", "add", "rm")):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g),
                                      err_msg=f"eager:{name}")
    # deferred chain under the skip/8 route + one finalize
    d1 = orset_fold_pallas_fused(*p, *b1, num_members=E, num_replicas=R,
                                 tile_cap=cap, interpret=True, h_blk=h_blk,
                                 retire_rm=False, hi_mode="skip",
                                 limb_bits=8)
    d2 = orset_fold_pallas_fused(*d1, *b2, num_members=E, num_replicas=R,
                                 tile_cap=cap, interpret=True, h_blk=h_blk,
                                 retire_rm=False, hi_mode="skip",
                                 limb_bits=8)
    dc, da, dr = d2
    got = orset_unpad_state(dc, da, orset_retire(dc, dr),
                            num_members=E, num_replicas=R)
    for r, g, name in zip(e2, got, ("clock", "add", "rm")):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g),
                                      err_msg=f"deferred:{name}")


def test_fused_big_counters_cond_limb8():
    """Counters ≥ 256 must stay exact through the 8-bit limb split with
    the data-dependent hi-limb cond."""
    from crdt_enc_tpu.ops.pallas_fold import (
        orset_fold_pallas_fused, orset_pad_state, orset_unpad_state,
    )

    E, R, N = 16, 300, 4000
    st = _well_formed_state(E, R, 11)
    b = _gen(N, E, R, 3, max_counter=MAX_COUNTER)
    cap = 1 << 13
    ref = orset_fold_pallas(*st, *b, num_members=E, num_replicas=R,
                            tile_cap=cap, interpret=True)
    p = orset_pad_state(*st, num_members=E, num_replicas=R)
    out = orset_fold_pallas_fused(*p, *b, num_members=E, num_replicas=R,
                                  tile_cap=cap, interpret=True,
                                  hi_mode="cond", limb_bits=8)
    got = orset_unpad_state(*out, num_members=E, num_replicas=R)
    for r, g, name in zip(ref, got, ("clock", "add", "rm")):
        np.testing.assert_array_equal(np.asarray(r), np.asarray(g),
                                      err_msg=name)


def test_fused_defaults_routing():
    from crdt_enc_tpu.ops.pallas_fold import fused_defaults

    d = fused_defaults(4096, 10_000, 132)
    assert d == dict(h_blk=32, hi_mode="skip", limb_bits=8)
    d = fused_defaults(4096, 10_000, 300)
    assert d["hi_mode"] == "cond" and d["limb_bits"] == 8
    assert fused_defaults(64, 1000, 10)["h_blk"] == 8  # H=8 -> single block

"""Kernel ≡ host byte-equality: the framework's central correctness contract.

Every jitted fold/merge must produce exactly the canonical serialized state
the host-reference engine produces (SURVEY.md §7: "byte-identical resulting
state").  Runs on the virtual CPU mesh in CI; the same code path runs on TPU
in bench.py.
"""

import uuid

import numpy as np
from _hyp import given, settings, st  # hypothesis, or skip-stubs

from crdt_enc_tpu.models import (
    GCounter,
    LWWMap,
    MVReg,
    ORSet,
    PNCounter,
    canonical_bytes,
)
from crdt_enc_tpu import ops as K

ACTORS = [uuid.UUID(int=i + 1).bytes for i in range(5)]
MEMBERS = [b"a", b"b", b"c", b"d"]

orset_script = st.lists(
    st.tuples(
        st.integers(0, len(ACTORS) - 1),
        st.sampled_from(["add", "rm"]),
        st.integers(0, len(MEMBERS) - 1),
    ),
    max_size=30,
)


def run_script(script, state=None):
    state = state if state is not None else ORSet()
    ops = []
    for actor_i, kind, member_i in script:
        actor, member = ACTORS[actor_i], MEMBERS[member_i]
        if kind == "add":
            op = state.add_ctx(actor, member)
        else:
            op = state.rm_ctx(member)
            if op.ctx.is_empty():
                continue
        state.apply(op)
        ops.append(op)
    return state, ops


def _bucket(n: int) -> int:
    """Next power-of-two padding bucket — bounds jit recompilation."""
    b = 8
    while b < n:
        b *= 2
    return b


def fixed_vocabs():
    """Full fixed vocabularies so kernel shapes are identical across
    hypothesis examples (one compilation, hundreds of examples)."""
    return K.Vocab(MEMBERS), K.Vocab(ACTORS)


def fold_on_device(initial: ORSet, ops, pad_to=None, **fold_kw):
    """Host initial state + op batch → kernel fold → host state."""
    members, replicas = fixed_vocabs()
    clock0, add0, rm0 = K.orset_state_to_planes(initial, members, replicas)
    cols = K.orset_ops_to_columns(ops, members, replicas)
    E, R = len(members), len(replicas)
    n = len(cols.kind)
    pad_to = max(pad_to or 0, _bucket(n))
    if pad_to > n:  # bucket padding with sentinel rows
        padn = pad_to - n
        cols.kind = np.concatenate([cols.kind, np.zeros(padn, np.int8)])
        cols.member = np.concatenate([cols.member, np.zeros(padn, np.int32)])
        cols.actor = np.concatenate([cols.actor, np.full(padn, R, np.int32)])
        cols.counter = np.concatenate([cols.counter, np.zeros(padn, np.int32)])
    clock, add, rm = K.orset_fold(
        clock0,
        add0,
        rm0,
        cols.kind,
        cols.member,
        cols.actor,
        cols.counter,
        num_members=E,
        num_replicas=R,
        **fold_kw,
    )
    return K.orset_planes_to_state(clock, add, rm, members, replicas)


@settings(max_examples=120, deadline=None)
@given(orset_script)
def test_orset_fold_matches_host(script):
    host, ops = run_script(script)
    if not ops:
        return
    device = fold_on_device(ORSet(), ops)
    assert canonical_bytes(device) == canonical_bytes(host)


@settings(max_examples=60, deadline=None)
@given(orset_script)
def test_orset_fold_sorted_segments_matches_host(script):
    """The sorted-scatter variant must be bit-identical to the default."""
    host, ops = run_script(script)
    if not ops:
        return
    device = fold_on_device(
        ORSet(), ops, impl="two_pass", sort_segments=True
    )
    assert canonical_bytes(device) == canonical_bytes(host)


@settings(max_examples=60, deadline=None)
@given(orset_script)
def test_orset_fold_two_pass_matches_host(script):
    """The original two-scatter variant must stay bit-identical."""
    host, ops = run_script(script)
    if not ops:
        return
    device = fold_on_device(ORSet(), ops, impl="two_pass")
    assert canonical_bytes(device) == canonical_bytes(host)


@settings(max_examples=60, deadline=None)
@given(orset_script, orset_script)
def test_orset_fold_fused_i16_from_nonempty_state(script_a, script_b):
    """int16 fast path (counters < 2**15), incl. nonzero initial planes."""
    base, _ = run_script(script_a)
    host2, ops = run_script(script_b, ORSet.from_obj(base.to_obj()))
    if not ops:
        return
    device = fold_on_device(
        ORSet.from_obj(base.to_obj()), ops, small_counters=True
    )
    assert canonical_bytes(device) == canonical_bytes(host2)


@settings(max_examples=60, deadline=None)
@given(orset_script, orset_script)
def test_orset_fold_from_nonempty_state(script_a, script_b):
    base, _ = run_script(script_a)
    host = ORSet.from_obj(base.to_obj())
    host2, ops = run_script(script_b, host)
    if not ops:
        return
    device = fold_on_device(ORSet.from_obj(base.to_obj()), ops)
    assert canonical_bytes(device) == canonical_bytes(host2)


def test_orset_fold_with_padding():
    host, ops = run_script([(0, "add", 0), (1, "add", 1), (0, "rm", 0), (2, "add", 0)])
    device = fold_on_device(ORSet(), ops, pad_to=64)
    assert canonical_bytes(device) == canonical_bytes(host)


@settings(max_examples=60, deadline=None)
@given(orset_script, orset_script)
def test_orset_merge_matches_host(script_a, script_b):
    sa, _ = run_script(script_a)
    sb, _ = run_script(script_b)
    host = ORSet.from_obj(sa.to_obj())
    host.merge(sb)

    members, replicas = fixed_vocabs()
    ca, aa, ra = K.orset_state_to_planes(sa, members, replicas)
    cb, ab, rb = K.orset_state_to_planes(sb, members, replicas)
    clock, add, rm = K.orset_merge(ca, aa, ra, cb, ab, rb)
    device = K.orset_planes_to_state(clock, add, rm, members, replicas)
    assert canonical_bytes(device) == canonical_bytes(host)


def test_orset_merge_many_tree():
    states = []
    for i in range(5):
        s, _ = run_script([(i % 5, "add", i % 4), ((i + 1) % 5, "add", (i + 2) % 4)])
        states.append(s)
    host = ORSet()
    for s in states:
        host.merge(s)

    members, replicas = fixed_vocabs()
    planes = [K.orset_state_to_planes(s, members, replicas) for s in states]
    clocks = np.stack([p[0] for p in planes])
    adds = np.stack([p[1] for p in planes])
    rms = np.stack([p[2] for p in planes])
    clock, add, rm = K.orset_merge_many(clocks, adds, rms)
    device = K.orset_planes_to_state(clock, add, rm, members, replicas)
    assert canonical_bytes(device) == canonical_bytes(host)


# ---- counters ------------------------------------------------------------


@settings(max_examples=80, deadline=None)
@given(
    st.lists(
        st.tuples(
            st.integers(0, 4), st.sampled_from(["inc", "dec"]), st.integers(1, 6)
        ),
        min_size=1,
        max_size=40,
    )
)
def test_pncounter_fold_matches_host(script):
    host = PNCounter()
    ops = []
    for actor_i, kind, steps in script:
        a = ACTORS[actor_i]
        op = host.inc(a, steps) if kind == "inc" else host.dec(a, steps)
        host.apply(op)
        ops.append(op)
    cols = K.counter_ops_to_columns(ops, replicas=K.Vocab(ACTORS))
    R = len(cols.replicas)
    n_rows = len(cols.sign)
    pad = _bucket(n_rows) - n_rows
    sign = np.concatenate([cols.sign, np.zeros(pad, np.int8)])
    actor = np.concatenate([cols.actor, np.full(pad, R, np.int32)])
    counter = np.concatenate([cols.counter, np.zeros(pad, np.int32)])
    p0 = np.zeros(R, np.int32)
    n0 = np.zeros(R, np.int32)
    p, n, value = K.pncounter_fold(p0, n0, sign, actor, counter, num_replicas=R)
    device = PNCounter(
        GCounter(K.dense_to_vclock(p, cols.replicas)),
        GCounter(K.dense_to_vclock(n, cols.replicas)),
    )
    assert int(value) == host.read()
    assert canonical_bytes(device) == canonical_bytes(host)


def test_gcounter_fold_matches_host():
    host = GCounter()
    ops = []
    for i in range(20):
        op = host.inc(ACTORS[i % 5], (i % 3) + 1)
        host.apply(op)
        ops.append(op)
    cols = K.counter_ops_to_columns(ops)
    R = len(cols.replicas)
    clock, value = K.gcounter_fold(
        np.zeros(R, np.int32), cols.actor, cols.counter, num_replicas=R
    )
    device = GCounter(K.dense_to_vclock(clock, cols.replicas))
    assert int(value) == host.read()
    assert canonical_bytes(device) == canonical_bytes(host)


# ---- LWW -----------------------------------------------------------------


@settings(max_examples=80, deadline=None)
@given(
    st.lists(
        st.tuples(
            st.integers(0, 4),  # actor
            st.integers(0, 3),  # key
            st.integers(0, 15),  # ts
            st.integers(0, 4),  # value
            st.booleans(),
        ),
        min_size=1,
        max_size=30,
    )
)
def test_lww_fold_matches_host(script):
    host = LWWMap()
    ops = []
    for actor_i, key_i, ts, val, tomb in script:
        a = ACTORS[actor_i]
        op = host.delete(key_i, ts, a) if tomb else host.put(key_i, ts, a, val)
        host.apply(op)
        ops.append(op)
    device = lww_fold_on_device(ops, keys=K.Vocab([0, 1, 2, 3]))
    assert canonical_bytes(device) == canonical_bytes(host)


def lww_fold_on_device(ops, keys=None) -> LWWMap:
    cols = K.lww_ops_to_columns(ops, keys=keys)
    Kn = len(cols.keys)
    n_rows = len(cols.key)
    pad = _bucket(n_rows) - n_rows
    key = np.concatenate([cols.key, np.full(pad, Kn, np.int32)])
    ts_hi = np.concatenate([cols.ts_hi, np.zeros(pad, np.int32)])
    ts_lo = np.concatenate([cols.ts_lo, np.zeros(pad, np.int32)])
    actor = np.concatenate([cols.actor, np.zeros(pad, np.int32)])
    value = np.concatenate([cols.value, np.zeros(pad, np.int32)])
    m_hi, m_lo, m_actor, m_value, present = K.lww_fold(
        key, ts_hi, ts_lo, actor, value, num_keys=Kn
    )
    device = LWWMap()
    for k in range(Kn):
        if not bool(present[k]):
            continue  # key in vocab but no ops touched it
        ts = (int(m_hi[k]) << 31) | int(m_lo[k])
        val = cols.values_sorted[int(m_value[k])]
        # find tombstone-ness: winner rows with this (key, ts, actor, value)
        mask = (
            (cols.key == k)
            & (cols.ts_hi == int(m_hi[k]))
            & (cols.ts_lo == int(m_lo[k]))
            & (cols.actor == int(m_actor[k]))
            & (cols.value == int(m_value[k]))
        )
        tomb = bool(cols.tombstone[np.nonzero(mask)[0][0]])
        device.entries[cols.keys.items[k]] = [
            ts,
            cols.actors_sorted[int(m_actor[k])],
            None if tomb else val,
            tomb,
        ]
    return device


def test_lww_fold_large_timestamps():
    # unix-nanos-scale timestamps must not truncate (the int32/x64 trap)
    base = 1_753_000_000_000_000_000  # ≈ 2025 in unix nanos
    host = LWWMap()
    ops = []
    for i, (ts, a) in enumerate(
        [(base + 5, 0), (base + 9, 1), (base + 9, 2), (base + 1, 3)]
    ):
        op = host.put(b"k", ts, ACTORS[a], i)
        host.apply(op)
        ops.append(op)
    device = lww_fold_on_device(ops)
    assert canonical_bytes(device) == canonical_bytes(host)
    assert device.get(b"k") == 2  # ts tie at base+9 → higher actor wins


def test_lww_fold_into_equals_fold_of_whole():
    # fold(A ++ B) == fold_into(fold(A), B): the incremental fold is exact
    rng = np.random.default_rng(11)
    Kn, n = 8, 64
    key = rng.integers(0, Kn, n).astype(np.int32)
    ts_hi = rng.integers(0, 4, n).astype(np.int32)
    ts_lo = rng.integers(0, 100, n).astype(np.int32)
    actor = rng.integers(0, 5, n).astype(np.int32)
    value = rng.integers(0, 20, n).astype(np.int32)

    whole = K.lww_fold(key, ts_hi, ts_lo, actor, value, num_keys=Kn)
    h = n // 2
    first = K.lww_fold(key[:h], ts_hi[:h], ts_lo[:h], actor[:h], value[:h], num_keys=Kn)
    second = K.lww_fold_into(
        first, key[h:], ts_hi[h:], ts_lo[h:], actor[h:], value[h:], num_keys=Kn
    )
    for a, b in zip(whole, second):
        assert np.array_equal(np.asarray(a), np.asarray(b))


# ---- MVReg ---------------------------------------------------------------


def test_mvreg_dominance_matches_host():
    r1, r2, r3 = MVReg(), MVReg(), MVReg()
    r1.apply(r1.write_ctx(ACTORS[0], b"a"))
    r2.apply(r2.write_ctx(ACTORS[1], b"b"))
    r3.merge(r1)
    r3.apply(r3.write_ctx(ACTORS[2], b"c"))  # supersedes r1's write
    host = MVReg()
    for r in (r1, r2, r3):
        host.merge(r)

    pairs = []
    for r in (r1, r2, r3):
        pairs.extend(r.vals)
    # host-side (clock, value) dedup per kernel contract
    seen = {}
    for c, v in pairs:
        seen[canonical_bytes(MVReg([(c, v)]))] = (c, v)
    pairs = list(seen.values())
    replicas = K.Vocab()
    for c, _ in pairs:
        for a in c.counters:
            replicas.intern(a)
    clocks = np.stack([K.vclock_to_dense(c, replicas) for c, _ in pairs])
    keep = K.mvreg_dominance_keep(clocks, np.ones(len(pairs), bool))
    device = MVReg([p for p, k in zip(pairs, keep.tolist()) if k])
    device._canonicalize()
    assert canonical_bytes(device) == canonical_bytes(host)


def test_orset_fold_coo_matches_dense():
    """The device sparse kernel (sort + run-max COO) must agree with the
    dense scatter fold, including against a non-zero starting clock."""
    from crdt_enc_tpu.ops.columnar import orset_apply_coo, orset_planes_to_state

    rng = np.random.default_rng(21)
    E, R, n = 16, 8, 256
    kind = (rng.random(n) < 0.3).astype(np.int8)
    member = rng.integers(0, E, n).astype(np.int32)
    actor = rng.integers(0, R + 1, n).astype(np.int32)  # R ⇒ padding rows
    counter = rng.integers(1, 12, n).astype(np.int32)
    clock0 = rng.integers(0, 4, R).astype(np.int32)

    members = K.Vocab(range(E))
    replicas = K.Vocab(ACTORS[:R]) if len(ACTORS) >= R else K.Vocab(
        [bytes([i] * 16) for i in range(R)]
    )

    dense = K.orset_fold(
        clock0, np.zeros((E, R), np.int32), np.zeros((E, R), np.int32),
        kind, member, actor, counter, num_members=E, num_replicas=R,
    )
    dense_state = orset_planes_to_state(
        np.asarray(dense[0]), np.asarray(dense[1]), np.asarray(dense[2]),
        members, replicas,
    )

    clock, skey, smax, is_max = K.orset_fold_coo(
        clock0, kind, member, actor, counter, num_members=E, num_replicas=R
    )
    coo_state = ORSet()
    # seed the starting clock exactly as the accel does
    coo_state.clock = K.dense_to_vclock(clock0, replicas)
    orset_apply_coo(
        coo_state, np.asarray(clock), np.asarray(skey), np.asarray(smax),
        np.asarray(is_max), members, replicas,
    )
    assert canonical_bytes(coo_state) == canonical_bytes(dense_state)


def test_orset_fold_stream_matches_whole_batch():
    """Chunked/donated streaming fold ≡ whole-batch fold ≡ host, on a
    causal history (the delivery contract the core guarantees)."""
    host, ops = run_script(
        [(i % 5, "add" if i % 4 else "rm", i % 4) for i in range(120)]
    )
    if not ops:
        return
    members, replicas = fixed_vocabs()
    cols = K.orset_ops_to_columns(ops, members, replicas)
    E, R = len(members), len(replicas)

    whole = fold_on_device(ORSet(), ops)

    clock, add, rm = K.orset_fold_stream(
        np.zeros(R, np.int32), np.zeros((E, R), np.int32),
        np.zeros((E, R), np.int32),
        K.iter_orset_chunks(cols.kind, cols.member, cols.actor, cols.counter,
                            chunk_rows=16, num_replicas=R),
        num_members=E, num_replicas=R,
    )
    streamed = K.orset_planes_to_state(
        np.asarray(clock), np.asarray(add), np.asarray(rm), members, replicas
    )
    assert canonical_bytes(streamed) == canonical_bytes(whole)
    assert canonical_bytes(streamed) == canonical_bytes(host)

    # the Pallas chunk route (interpret mode here; real MXU on TPU) must
    # produce the same planes; one tile_cap over the whole member column
    from crdt_enc_tpu.ops.pallas_fold import fold_cap

    clock, add, rm = K.orset_fold_stream(
        np.zeros(R, np.int32), np.zeros((E, R), np.int32),
        np.zeros((E, R), np.int32),
        K.iter_orset_chunks(cols.kind, cols.member, cols.actor, cols.counter,
                            chunk_rows=16, num_replicas=R),
        num_members=E, num_replicas=R, impl="pallas",
        tile_cap=fold_cap(cols.member, E),
    )
    streamed_p = K.orset_planes_to_state(
        np.asarray(clock), np.asarray(add), np.asarray(rm), members, replicas
    )
    assert canonical_bytes(streamed_p) == canonical_bytes(host)


# ---- round 5: sorted segment-max counter path (sort + run-end gather)


def test_counter_sorted_vs_scatter_paths():
    """The sorted (N ≥ SORTED_MIN_ROWS) and scatter routes must agree
    exactly — including pad rows, empty segments, and ties — and both
    must match a numpy reference."""
    import numpy as np

    import crdt_enc_tpu.ops.counters as C

    rng = np.random.default_rng(17)
    for R in (1, 7, 1000):
        N = 9000  # above SORTED_MIN_ROWS → sorted path
        actor = rng.integers(0, R + 1, N).astype(np.int32)
        sign = (rng.random(N) < 0.5).astype(np.int8)
        counter = rng.integers(0, 1 << 14, N).astype(np.int32)
        p0 = rng.integers(0, 100, R).astype(np.int32)
        n0 = rng.integers(0, 100, R).astype(np.int32)
        pe, ne = p0.copy(), n0.copy()
        for a, s, c in zip(actor, sign, counter):
            if a >= R:
                continue
            if s == 0:
                pe[a] = max(pe[a], c)
            else:
                ne[a] = max(ne[a], c)
        p, n, v = C.pncounter_fold(p0, n0, sign, actor, counter,
                                   num_replicas=R)
        np.testing.assert_array_equal(np.asarray(p), pe)
        np.testing.assert_array_equal(np.asarray(n), ne)
        assert int(v) == int(pe.sum()) - int(ne.sum())
        # scatter route on the same data (shrunk below the threshold)
        cut = C.SORTED_MIN_ROWS - 1
        ps, ns, _ = C.pncounter_fold(p0, n0, sign[:cut], actor[:cut],
                                     counter[:cut], num_replicas=R)
        pe2, ne2 = p0.copy(), n0.copy()
        for a, s, c in zip(actor[:cut], sign[:cut], counter[:cut]):
            if a >= R:
                continue
            if s == 0:
                pe2[a] = max(pe2[a], c)
            else:
                ne2[a] = max(ne2[a], c)
        np.testing.assert_array_equal(np.asarray(ps), pe2)
        np.testing.assert_array_equal(np.asarray(ns), ne2)


def test_counter_sorted_hypothesis():
    from _hyp import given, settings, st  # hypothesis, or skip-stubs

    import numpy as np

    import crdt_enc_tpu.ops.counters as C

    @settings(max_examples=25, deadline=None)
    @given(
        seed=st.integers(0, 2**31 - 1),
        r=st.integers(1, 40),
        pad_frac=st.floats(0, 0.5),
    )
    def run(seed, r, pad_frac):
        rng = np.random.default_rng(seed)
        # force the sorted route regardless of batch size by routing on
        # a monkeypatched threshold — the public API stays untouched
        N = 400
        actor = rng.integers(0, r, N).astype(np.int32)
        pad = rng.random(N) < pad_frac
        actor = np.where(pad, r, actor).astype(np.int32)
        counter = rng.integers(0, 3000, N).astype(np.int32)
        clock0 = rng.integers(0, 1500, r).astype(np.int32)
        ce = clock0.copy()
        for a, c in zip(actor, counter):
            if a < r:
                ce[a] = max(ce[a], c)
        old = C.SORTED_MIN_ROWS
        C.SORTED_MIN_ROWS = 1
        try:
            ck, tot = C.gcounter_fold.__wrapped__(
                clock0, actor, counter, num_replicas=r)
        finally:
            C.SORTED_MIN_ROWS = old
        np.testing.assert_array_equal(np.asarray(ck), ce)
        assert int(tot) == int(ce.sum())

    run()

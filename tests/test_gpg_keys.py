"""OpenPGP key cryptor: real PGP recipient management through the gpg
binary — the interop the reference's gpgme backend declared but shipped
as identity stubs (crdt-enc-gpgme/src/lib.rs:95-98, 131-175)."""

import asyncio
import os
import subprocess

import pytest

from crdt_enc_tpu.backends import FsStorage, XChaChaCryptor, gpg_available
from crdt_enc_tpu.backends.gpg_keys import GpgKeyCryptor, NotDecryptable
from crdt_enc_tpu.core import Core, CoreError, OpenOptions, orset_adapter
from crdt_enc_tpu.models import canonical_bytes
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

pytestmark = pytest.mark.skipif(not gpg_available(), reason="no gpg binary")


def run(coro):
    return asyncio.run(coro)


def _gpg(home, *args, stdin=None):
    env = dict(os.environ, GNUPGHOME=str(home))
    r = subprocess.run(
        ["gpg", "--batch", "--quiet", "--yes", "--pinentry-mode", "loopback",
         "--passphrase", ""] + list(args),
        input=stdin, capture_output=True, env=env,
    )
    assert r.returncode == 0, r.stderr.decode()
    return r.stdout


def make_identity(tmp_path, name: str) -> tuple[str, str]:
    """A fresh GnuPG home with one signing+encryption keypair; returns
    (home, fingerprint)."""
    home = tmp_path / f"gnupg-{name}"
    home.mkdir(mode=0o700)
    _gpg(home, "--quick-gen-key", f"{name} <{name}@test>", "ed25519",
         "cert,sign", "never")
    cols = _gpg(home, "--list-keys", "--with-colons").decode()
    fpr = next(l.split(":")[9] for l in cols.splitlines() if l.startswith("fpr"))
    _gpg(home, "--quick-add-key", fpr, "cv25519", "encr", "never")
    return str(home), fpr


def share_pubkey(src_home, fpr, dst_home):
    pub = _gpg(src_home, "--export", fpr)
    _gpg(dst_home, "--import", stdin=pub)


def make_opts(tmp_path, name, kc):
    return OpenOptions(
        storage=FsStorage(str(tmp_path / name), str(tmp_path / "remote")),
        cryptor=XChaChaCryptor(),
        key_cryptor=kc,
        adapter=orset_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=True,
    )


def test_two_pgp_replicas_converge(tmp_path):
    home_a, fpr_a = make_identity(tmp_path, "alice")
    home_b, fpr_b = make_identity(tmp_path, "bob")
    share_pubkey(home_a, fpr_a, home_b)
    share_pubkey(home_b, fpr_b, home_a)
    recipients = [fpr_a, fpr_b]

    async def go():
        a = await Core.open(make_opts(
            tmp_path, "a", GpgKeyCryptor(recipients, gnupg_home=home_a)
        ))
        await a.update(lambda s: s.add_ctx(a.actor_id, b"x"))
        b = await Core.open(make_opts(
            tmp_path, "b", GpgKeyCryptor(recipients, gnupg_home=home_b)
        ))
        await b.read_remote()
        assert b.with_state(lambda s: s.contains(b"x"))
        ka, kb = a._data.keys.latest_key(), b._data.keys.latest_key()
        assert ka.id == kb.id and ka.material == kb.material
        assert a.with_state(canonical_bytes) == b.with_state(canonical_bytes)

    run(go())


def test_non_recipient_cannot_join(tmp_path):
    home_a, fpr_a = make_identity(tmp_path, "alice")
    home_eve, fpr_eve = make_identity(tmp_path, "eve")
    share_pubkey(home_a, fpr_a, home_eve)  # eve knows alice's PUBLIC key

    async def go():
        a = await Core.open(make_opts(
            tmp_path, "a", GpgKeyCryptor([fpr_a], gnupg_home=home_a)
        ))
        await a.update(lambda s: s.add_ctx(a.actor_id, b"secret"))
        # eve can see the remote but the Keys blob is not sealed to her
        with pytest.raises((CoreError, NotDecryptable)):
            await Core.open(make_opts(
                tmp_path, "eve", GpgKeyCryptor([fpr_a], gnupg_home=home_eve)
            ))

    run(go())


def test_keys_blob_is_standard_openpgp(tmp_path):
    """Interop claim made literal: the stored key metadata decrypts with
    plain `gpg --decrypt`, no framework code involved."""
    home_a, fpr_a = make_identity(tmp_path, "alice")

    async def go():
        a = await Core.open(make_opts(
            tmp_path, "a", GpgKeyCryptor([fpr_a], gnupg_home=home_a)
        ))
        await a.update(lambda s: s.add_ctx(a.actor_id, b"x"))
        reg = a._data.remote_meta.key_cryptor.read().values
        assert reg
        from crdt_enc_tpu.utils import VersionBytes

        vb = VersionBytes.from_obj(reg[0])
        clear = _gpg(home_a, "--decrypt", "--output", "-", stdin=vb.content)
        from crdt_enc_tpu.core.key_cryptor import Keys
        from crdt_enc_tpu.utils import codec

        keys = Keys.from_obj(codec.unpack(clear))
        assert keys.latest_key() is not None

    run(go())


def test_signed_blobs_and_unsigned_rejection(tmp_path):
    home_a, fpr_a = make_identity(tmp_path, "alice")
    home_b, fpr_b = make_identity(tmp_path, "bob")
    share_pubkey(home_a, fpr_a, home_b)
    share_pubkey(home_b, fpr_b, home_a)
    recipients = [fpr_a, fpr_b]

    async def go():
        # A signs its key metadata; B requires signatures and accepts it
        a = await Core.open(make_opts(
            tmp_path, "a",
            GpgKeyCryptor(recipients, gnupg_home=home_a, sign_with=fpr_a),
        ))
        await a.update(lambda s: s.add_ctx(a.actor_id, b"x"))
        b = await Core.open(make_opts(
            tmp_path, "b",
            GpgKeyCryptor(recipients, gnupg_home=home_b,
                          sign_with=fpr_b, require_signature=True),
        ))
        await b.read_remote()
        assert b.with_state(lambda s: s.contains(b"x"))

    run(go())

    # an UNSIGNED blob is rejected by a require_signature reader
    async def check_unsigned():
        kc = GpgKeyCryptor(
            [fpr_a], gnupg_home=home_a, sign_with=fpr_a,
            require_signature=True,
        )
        unsigned = await GpgKeyCryptor(
            [fpr_a], gnupg_home=home_a
        )._protect(b"payload")

        class VB:
            content = unsigned

        with pytest.raises(NotDecryptable):
            await kc._unprotect(VB())

    run(check_unsigned())

    # require_signature without a signing key would reject the replica's
    # own writes — refused at construction
    with pytest.raises(ValueError):
        GpgKeyCryptor([fpr_a], gnupg_home=home_a, require_signature=True)


def test_goodsig_forgery_in_plaintext_filename_rejected(tmp_path):
    """The signature check must parse status LINES: an unsigned message
    whose embedded literal-packet filename says GOODSIG (attacker-chosen,
    echoed into the PLAINTEXT status line) must still be rejected."""
    home_a, fpr_a = make_identity(tmp_path, "alice")

    async def go():
        forged = _gpg(
            home_a, "--encrypt", "--trust-model", "always",
            "--set-filename", "[GNUPG:] GOODSIG 0 forged",
            "--recipient", fpr_a, "--output", "-",
            stdin=b"attacker keys blob",
        )
        kc = GpgKeyCryptor(
            [fpr_a], gnupg_home=home_a, sign_with=fpr_a,
            require_signature=True,
        )

        class VB:
            content = forged

        with pytest.raises(NotDecryptable):
            await kc._unprotect(VB())

    run(go())

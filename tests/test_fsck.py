"""Remote integrity checker (tools/fsck.py): a healthy remote reports OK;
every deliberately inflicted damage class is detected."""

import asyncio
import os

import pytest

from crdt_enc_tpu.backends import FsStorage, PlainKeyCryptor, XChaChaCryptor
from crdt_enc_tpu.core import Core, OpenOptions, orset_adapter
from crdt_enc_tpu.tools.fsck import fsck_remote, main as fsck_main
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


def run(coro):
    return asyncio.run(coro)


def make_opts(tmp_path, name):
    return OpenOptions(
        storage=FsStorage(str(tmp_path / name), str(tmp_path / "remote")),
        cryptor=XChaChaCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=orset_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=True,
    )


async def populate(tmp_path):
    a = await Core.open(make_opts(tmp_path, "a"))
    for m in range(6):
        await a.update(lambda s, m=m: s.add_ctx(a.actor_id, m))
    await a.compact()
    b = await Core.open(make_opts(tmp_path, "b"))
    for m in range(3):
        await b.update(lambda s, m=m: s.add_ctx(b.actor_id, 100 + m))
    return a, b


def checker(tmp_path):
    return fsck_remote(
        FsStorage(str(tmp_path / "fsck-local"), str(tmp_path / "remote")),
        XChaChaCryptor(),
        PlainKeyCryptor(),
    )


def test_healthy_remote_is_ok(tmp_path):
    async def go():
        await populate(tmp_path)
        report = await checker(tmp_path)
        assert report.ok, [str(i) for i in report.issues]
        assert report.state_files == 1
        assert report.op_files == 3  # b's tail; a's were GC'd by compact
        assert report.ops_decoded == 3
        assert report.keys_found >= 1
        assert "OK" in report.summary()

    run(go())


def test_detects_tampered_op_file(tmp_path):
    async def go():
        await populate(tmp_path)
        ops_root = tmp_path / "remote" / "ops"
        actor = sorted(os.listdir(ops_root))[0]
        target = ops_root / actor / "1"
        raw = bytearray(target.read_bytes())
        raw[-1] ^= 1
        target.write_bytes(bytes(raw))
        report = await checker(tmp_path)
        assert not report.ok
        assert any(i.family == "ops" for i in report.issues)

    run(go())


def test_detects_op_log_gap(tmp_path):
    async def go():
        await populate(tmp_path)
        ops_root = tmp_path / "remote" / "ops"
        actor = sorted(os.listdir(ops_root))[0]
        os.remove(ops_root / actor / "2")  # hole with file 3 beyond it
        report = await checker(tmp_path)
        assert not report.ok
        assert any("gap" in i.problem for i in report.issues)

    run(go())


def test_detects_content_address_mismatch_and_torn_state(tmp_path):
    async def go():
        await populate(tmp_path)
        states = tmp_path / "remote" / "states"
        name = os.listdir(states)[0]
        blob = (states / name).read_bytes()
        (states / name).write_bytes(blob[: len(blob) // 2])  # torn write
        report = await checker(tmp_path)
        assert not report.ok
        assert any(
            i.family == "states" and "address" in i.problem
            for i in report.issues
        )

    run(go())


def test_detects_damaged_key_metadata(tmp_path):
    async def go():
        await populate(tmp_path)
        meta = tmp_path / "remote" / "meta"
        for n in os.listdir(meta):
            os.remove(meta / n)
        report = await checker(tmp_path)
        assert not report.ok
        # ops are sealed with a key no surviving metadata can resolve
        assert any(i.family == "keys" or "unknown key" in i.problem
                   for i in report.issues)

    run(go())


def test_cli(tmp_path, capsys):
    async def go():
        await populate(tmp_path)

    run(go())
    rc = fsck_main([str(tmp_path / "remote")])
    out = capsys.readouterr().out
    assert rc == 0 and "OK" in out

    # damage → nonzero exit
    ops_root = tmp_path / "remote" / "ops"
    actor = sorted(os.listdir(ops_root))[0]
    target = ops_root / actor / "1"
    raw = bytearray(target.read_bytes())
    raw[-1] ^= 1
    target.write_bytes(bytes(raw))
    rc = fsck_main([str(tmp_path / "remote")])
    out = capsys.readouterr().out
    assert rc == 1 and "DAMAGED" in out


def test_post_compaction_tail_is_healthy(tmp_path):
    """Compaction GCs an op-log prefix, so a healthy log legitimately
    starts beyond version 1 — fsck must anchor its dense-scan check at
    the floor, not report a phantom gap (review regression)."""

    async def go():
        a, b = await populate(tmp_path)
        # b keeps writing, someone compacts, b writes again: b's log now
        # starts past the GC'd prefix
        await b.compact()
        for m in range(2):
            await b.update(lambda s, m=m: s.add_ctx(b.actor_id, 200 + m))
        report = await checker(tmp_path)
        assert report.ok, [str(i) for i in report.issues]
        assert report.op_files == 2  # just the post-compaction tail

    run(go())


def test_detects_malformed_snapshot_sealer(tmp_path):
    """Snapshots may carry a third element — the sealer's 16-byte actor
    id (replication obs).  A healthy remote's sealed snapshots pass
    (covered above); a wrong-width sealer is flagged, not ignored."""

    async def go():
        a, _b = await populate(tmp_path)
        state_obj = a.with_state(lambda s: a.adapter.state_to_obj(s))
        bad = await a._seal(  # noqa: SLF001 — white-box wire forgery
            [state_obj, {}, b"short"]
        )
        await a.storage.store_state(bad)
        report = await checker(tmp_path)
        assert not report.ok
        assert any(
            "sealer id is not 16 bytes" in i.problem for i in report.issues
        )

    run(go())


def test_dangling_latest_key_reported_not_crash(tmp_path):
    """A latest-id register that survives while its key material is lost
    must produce a keys issue, not an unhandled DanglingLatestKey."""

    async def go():
        await populate(tmp_path)
        report = await checker(tmp_path)
        assert report.ok

        # simulate the damage at the decode layer: keys material vanishes
        from crdt_enc_tpu.models import ORSet

        class DamagedKeyCryptor(PlainKeyCryptor):
            async def set_remote_meta(self, reg):
                await super().set_remote_meta(reg)
                if self._core is not None:
                    damaged = self._core.keys
                    damaged.keys = ORSet()  # material gone, latest id kept
                    damaged._index = None

        report = await fsck_remote(
            FsStorage(str(tmp_path / "fsck2"), str(tmp_path / "remote")),
            XChaChaCryptor(),
            DamagedKeyCryptor(),
        )
        assert not report.ok
        assert any(i.family == "keys" for i in report.issues)

    run(go())

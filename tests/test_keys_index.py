"""Keys.get_key id→key index: O(1) lookups over a long rotation history
(reference key_cryptor.rs:55-57 scans the Orswot per call; a bulk ingest
calls get_key per key group, so the lookup must not re-sort the whole
history each time)."""

import secrets

from crdt_enc_tpu.core.key_cryptor import DanglingLatestKey, Key, Keys
from crdt_enc_tpu.utils import codec
from crdt_enc_tpu.utils.version_bytes import VersionBytes

ACTOR_A = b"A" * 16
ACTOR_B = b"B" * 16


def fresh_key() -> Key:
    return Key.new(VersionBytes(b"\x00" * 16, secrets.token_bytes(32)))


def test_rotation_history_lookup_correct_and_cached():
    keys = Keys()
    history = [fresh_key() for _ in range(100)]
    for k in history:
        keys.insert_latest_key(ACTOR_A, k)
    # every id in the rotation history resolves to its exact material
    for k in history:
        got = keys.get_key(k.id)
        assert got is not None and got.material == k.material
    assert keys.latest_key().id == history[-1].id
    # index is cached: repeated lookups return the same object, and no
    # rebuild happens between calls (identity check is the cheap proxy)
    assert keys.get_key(history[0].id) is keys.get_key(history[0].id)
    assert keys.get_key(b"\xff" * 16) is None


def test_index_invalidated_by_insert_and_merge():
    keys = Keys()
    k1 = fresh_key()
    keys.insert_latest_key(ACTOR_A, k1)
    assert keys.get_key(k1.id) is not None  # index built

    k2 = fresh_key()
    keys.insert_latest_key(ACTOR_A, k2)  # must invalidate
    assert keys.get_key(k2.id) is not None
    assert keys.latest_key().id == k2.id

    other = Keys()
    k3 = fresh_key()
    other.insert_latest_key(ACTOR_B, k3)
    keys.merge(other)  # must invalidate
    assert keys.get_key(k3.id) is not None
    assert keys.get_key(k1.id) is not None


def test_index_survives_serialization_roundtrip():
    keys = Keys()
    ks = [fresh_key() for _ in range(5)]
    for k in ks:
        keys.insert_latest_key(ACTOR_A, k)
    back = Keys.from_obj(codec.unpack(codec.pack(keys.to_obj())))
    for k in ks:
        got = back.get_key(k.id)
        assert got is not None and got.material == k.material
    assert back.latest_key().id == keys.latest_key().id


def test_dangling_latest_still_raises():
    keys = Keys()
    k = fresh_key()
    keys.insert_latest_key(ACTOR_A, k)
    keys.keys = type(keys.keys)()  # drop all key material behind its back
    keys._index = None
    import pytest

    with pytest.raises(DanglingLatestKey):
        keys.latest_key()


def test_no_quadratic_blowup_on_bulk_lookup():
    """200-key history, 2000 lookups: with the index this is ~one pass to
    build + dict hits; the old path was 2000 × (sort 200 members × msgpack).
    Assert work done, not wall-clock (CI-stable): count codec.pack calls."""
    keys = Keys()
    history = [fresh_key() for _ in range(200)]
    for k in history:
        keys.insert_latest_key(ACTOR_A, k)

    calls = 0
    real_pack = codec.pack

    def counting_pack(obj):
        nonlocal calls
        calls += 1
        return real_pack(obj)

    import crdt_enc_tpu.core.key_cryptor as kc_mod

    probe = type(codec)("codec_probe")
    probe.pack = counting_pack
    kc_mod.codec = probe
    try:
        for _ in range(10):
            for k in history:
                assert keys.get_key(k.id) is not None
    finally:
        kc_mod.codec = codec
    # index build may pack during construction; lookups after that must not
    assert calls == 0, f"get_key packed {calls} times on cached index"

"""Reference-remote importer: golden fixtures in the reference's wire
format (synthesized byte-layer-exact from its in-tree serialization code —
see the layer citations in tools/import_reference.py) round-trip through
import → read_remote → compact in this framework.

Fixture layers per the reference source:
* outer: raw VersionBytes = CURRENT_VERSION uuid bytes ‖ payload
  (crdt-enc/src/lib.rs:26, 695; version_bytes.rs:198-208)
* cipher: rmp to_vec_named of VersionBytesRef(DATA_VERSION, EncBox) —
  tuple struct → msgpack array, uuid → bin16, EncBox named struct →
  {"nonce": bin24, "enc_data": bin} (xchacha lib.rs:59-68)
* inner: raw VersionBytes(app data version) ‖ rmp(Vec<Op>)
  (lib.rs:670-671)
* op dirs: actor uuid Display form, files from version 0
  (crdt-enc-tokio lib.rs:249-257; lib.rs:697-716)
"""

import asyncio
import secrets
import uuid as uuidm

import pytest

from crdt_enc_tpu.backends import (
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PlainKeyCryptor,
    XChaChaCryptor,
    FsStorage,
)
from crdt_enc_tpu.backends.xchacha import seal_raw
from crdt_enc_tpu.core import Core, OpenOptions, mvreg_adapter
from crdt_enc_tpu.models import MVReg, canonical_bytes
from crdt_enc_tpu.tools.import_reference import (
    REF_CIPHER_DATA_VERSION,
    REF_CONTAINER_VERSION,
    ReferenceFormatError,
    import_reference_remote,
    mvreg_translator,
    open_reference_blob,
)
from crdt_enc_tpu.utils import codec
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

APP_DATA_VERSION = uuidm.UUID("11111111-2222-3333-4444-555555555555").bytes

ACTOR_A = uuidm.UUID(int=0xA).bytes
ACTOR_B = uuidm.UUID(int=0xB).bytes
ACTOR_C = uuidm.UUID(int=0xC).bytes


def run(coro):
    return asyncio.run(coro)


# ---- fixture synthesis (the reference's exact layering) -------------------


def ref_seal(key: bytes, payload: bytes, data_version=APP_DATA_VERSION) -> bytes:
    inner = data_version + payload
    nonce = secrets.token_bytes(24)
    enc_box = codec.pack({"nonce": nonce, "enc_data": seal_raw(key, nonce, inner)})
    middle = codec.pack([REF_CIPHER_DATA_VERSION, enc_box])
    return REF_CONTAINER_VERSION + middle


def ref_mvreg_op(clock: dict, val, named=True):
    """crdts v7 mvreg::Op { clock, val } — named-map (to_vec_named) or
    positional encodings."""
    clk = {"dots": dict(clock)} if named else list([dict(clock)])[0]
    return {"clock": clk, "val": val} if named else [dict(clock), val]


def write_ref_remote(root, key, files_by_actor):
    """files_by_actor: {actor_bytes: [ [op, ...] per file ]} — written in
    the reference layout (Display-named dirs, versions from 0)."""
    for actor, files in files_by_actor.items():
        d = root / "ops" / str(uuidm.UUID(bytes=actor))
        d.mkdir(parents=True)
        for v, ops in enumerate(files):
            (d / str(v)).write_bytes(ref_seal(key, codec.pack(ops)))


def make_dest(tmp_path, name="dest"):
    return OpenOptions(
        storage=FsStorage(str(tmp_path / name / "local"), str(tmp_path / name / "remote")),
        cryptor=XChaChaCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=mvreg_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=True,
    )


# ---- blob-level ------------------------------------------------------------


def test_open_reference_blob_roundtrip():
    key = secrets.token_bytes(32)
    payload = codec.pack([ref_mvreg_op({ACTOR_A: 1}, 7)])
    blob = ref_seal(key, payload)
    ver, out = open_reference_blob(key, blob)
    assert ver == APP_DATA_VERSION
    assert bytes(out) == payload


def test_open_reference_blob_rejects_wrong_key_and_formats():
    key = secrets.token_bytes(32)
    blob = ref_seal(key, b"x")
    from crdt_enc_tpu.backends.xchacha import AeadError

    with pytest.raises(AeadError):
        open_reference_blob(secrets.token_bytes(32), blob)
    with pytest.raises(ReferenceFormatError):
        open_reference_blob(key, b"\x00" * 40)  # wrong container uuid
    tampered = blob[:16] + codec.pack([APP_DATA_VERSION, b"junk"])
    with pytest.raises(ReferenceFormatError):
        open_reference_blob(key, tampered)  # wrong cipher version


def test_mvreg_translator_accepts_both_encodings():
    named = codec.pack([ref_mvreg_op({ACTOR_A: 3}, 42, named=True)])
    positional = codec.pack([ref_mvreg_op({ACTOR_A: 3}, 42, named=False)])
    for payload in (named, positional):
        (op,) = mvreg_translator(payload)
        assert op.value == 42
        assert op.clock.get(ACTOR_A) == 3


# ---- end-to-end ------------------------------------------------------------


def test_import_reference_remote_end_to_end(tmp_path):
    """Three reference actors with a write history including dominated and
    concurrent register writes; import → fold → compact → fresh replica
    re-joins from the snapshot alone."""
    key = secrets.token_bytes(32)
    src = tmp_path / "ref-remote"
    # A writes 1 (clock {A:1}); B overwrites with 2 ({A:1,B:1});
    # C writes 3 concurrently with B ({A:1,C:1}) → values {2, 3} survive
    write_ref_remote(src, key, {
        ACTOR_A: [[ref_mvreg_op({ACTOR_A: 1}, 1)]],
        ACTOR_B: [[ref_mvreg_op({ACTOR_A: 1, ACTOR_B: 1}, 2)]],
        ACTOR_C: [[ref_mvreg_op({ACTOR_A: 1, ACTOR_C: 1}, 3)]],
    })

    async def go():
        dest = await Core.open(make_dest(tmp_path))
        stats = await import_reference_remote(src, dest, key, compact=True)
        assert stats.actors == 3 and stats.op_files == 3 and stats.ops == 3
        assert stats.data_versions == {APP_DATA_VERSION}
        assert sorted(dest.with_state(lambda s: s.read().values)) == [2, 3]

        # the snapshot alone carries the imported history
        fresh2 = await Core.open(OpenOptions(
            storage=FsStorage(
                str(tmp_path / "fresh2"), str(tmp_path / "dest" / "remote")
            ),
            cryptor=XChaChaCryptor(),
            key_cryptor=PlainKeyCryptor(),
            adapter=mvreg_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=True,
        ))
        await fresh2.read_remote()
        assert fresh2.with_state(canonical_bytes) == dest.with_state(
            canonical_bytes
        )

    run(go())


def test_import_multi_file_histories_and_version_shift(tmp_path):
    """Multi-file per-actor logs (reference versions 0,1,2…) must land
    densely at destination versions 1,2,3… and fold in order."""
    key = secrets.token_bytes(32)
    src = tmp_path / "ref-remote"
    write_ref_remote(src, key, {
        ACTOR_A: [
            [ref_mvreg_op({ACTOR_A: 1}, 10)],
            [ref_mvreg_op({ACTOR_A: 2}, 11)],
            [ref_mvreg_op({ACTOR_A: 3}, 12), ref_mvreg_op({ACTOR_A: 4}, 13)],
        ],
    })

    async def go():
        dest = await Core.open(make_dest(tmp_path))
        stats = await import_reference_remote(src, dest, key)
        assert stats.op_files == 3 and stats.ops == 4
        # dominated writes resolved: only the latest survives
        assert dest.with_state(lambda s: s.read().values) == [13]
        # dest remote holds the imported files at versions 1..3
        names = sorted(
            int(n) for n in
            __import__("os").listdir(
                tmp_path / "dest" / "remote" / "ops" / ACTOR_A.hex()
            )
            if not n.startswith(".")
        )
        assert names == [1, 2, 3]

    run(go())


def test_import_skips_reference_states_and_warns(tmp_path, caplog):
    key = secrets.token_bytes(32)
    src = tmp_path / "ref-remote"
    write_ref_remote(src, key, {ACTOR_A: [[ref_mvreg_op({ACTOR_A: 1}, 5)]]})
    (src / "states").mkdir()
    (src / "states" / "somehash").write_bytes(b"unreadable by design")

    async def go():
        dest = await Core.open(make_dest(tmp_path))
        with caplog.at_level("WARNING"):
            stats = await import_reference_remote(src, dest, key)
        assert stats.skipped_states == 1
        assert any("SURVEY.md" in r.message for r in caplog.records)
        assert dest.with_state(lambda s: s.read().values) == [5]

    run(go())


def test_import_cli(tmp_path, capsys):
    from crdt_enc_tpu.tools.import_reference import main

    key = secrets.token_bytes(32)
    src = tmp_path / "ref-remote"
    write_ref_remote(src, key, {
        ACTOR_A: [[ref_mvreg_op({ACTOR_A: 1}, 5)]],
        ACTOR_B: [[ref_mvreg_op({ACTOR_A: 1, ACTOR_B: 1}, 6)]],
    })
    rc = main([
        str(src), str(tmp_path / "d-local"), str(tmp_path / "d-remote"),
        "--key-hex", key.hex(), "--compact",
    ])
    assert rc == 0
    assert "imported 2 ops in 2 files from 2 actors" in capsys.readouterr().out

    async def check():
        reader = await Core.open(OpenOptions(
            storage=FsStorage(str(tmp_path / "r"), str(tmp_path / "d-remote")),
            cryptor=XChaChaCryptor(),
            key_cryptor=PlainKeyCryptor(),
            adapter=mvreg_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=True,
        ))
        await reader.read_remote()
        assert reader.with_state(lambda s: s.read().values) == [6]

    run(check())


def test_import_refuses_gapped_history(tmp_path):
    """A missing version file with later files present means the source log
    is not dense — the importer must refuse, never silently truncate."""
    import os as _os

    key = secrets.token_bytes(32)
    src = tmp_path / "ref-remote"
    write_ref_remote(src, key, {
        ACTOR_A: [
            [ref_mvreg_op({ACTOR_A: 1}, 1)],
            [ref_mvreg_op({ACTOR_A: 2}, 2)],
            [ref_mvreg_op({ACTOR_A: 3}, 3)],
        ],
    })
    _os.remove(src / "ops" / str(uuidm.UUID(bytes=ACTOR_A)) / "1")

    async def go():
        dest = await Core.open(make_dest(tmp_path))
        with pytest.raises(ReferenceFormatError, match="gap"):
            await import_reference_remote(src, dest, key)

    run(go())

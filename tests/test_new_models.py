"""Property tests for the round-2 model additions (GSet, LWWReg,
MerkleReg, SeqList): CRDT laws under adversarial interleavings, plus a
full Core lifecycle per type — same strategy as tests/test_crdt_laws.py
(oracle-derived causally consistent histories, per-actor order
preserved, cross-actor interleaving chosen by hypothesis)."""

import asyncio
import copy
import uuid

import pytest
from _hyp import given, settings, st  # hypothesis, or skip-stubs

from crdt_enc_tpu.backends import (
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PlainKeyCryptor,
)
from crdt_enc_tpu.core import (
    Core,
    OpenOptions,
    gset_adapter,
    list_adapter,
    lwwreg_adapter,
    merklereg_adapter,
)
from crdt_enc_tpu.models import (
    GSet,
    LWWReg,
    MerkleReg,
    SeqList,
    canonical_bytes,
)
from crdt_enc_tpu.models.seqlist import path_between
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

ACTORS = [uuid.UUID(int=i + 1).bytes for i in range(4)]


def interleave(streams, rng):
    streams = [list(s) for s in streams if s]
    out = []
    while streams:
        i = rng.draw(st.integers(0, len(streams) - 1))
        out.append(streams[i].pop(0))
        if not streams[i]:
            streams.pop(i)
    return out


def merge_laws(states, make_new):
    """Commutativity, associativity, idempotence over the given states."""
    a, b = copy.deepcopy(states[0]), copy.deepcopy(states[-1])
    ab, ba = copy.deepcopy(a), copy.deepcopy(b)
    ab.merge(b)
    ba.merge(a)
    assert canonical_bytes(ab) == canonical_bytes(ba)  # commutative
    ab2 = copy.deepcopy(ab)
    ab2.merge(b)
    assert canonical_bytes(ab2) == canonical_bytes(ab)  # idempotent
    if len(states) >= 3:
        x, y, z = (copy.deepcopy(s) for s in states[:3])
        left = copy.deepcopy(x)
        left.merge(y)
        left.merge(z)
        yz = copy.deepcopy(y)
        yz.merge(z)
        right = copy.deepcopy(x)
        right.merge(yz)
        assert canonical_bytes(left) == canonical_bytes(right)  # associative


# ---- SeqList --------------------------------------------------------------

list_script = st.lists(
    st.tuples(
        st.integers(0, len(ACTORS) - 1),
        st.sampled_from(["ins", "del"]),
        st.integers(0, 10),
        st.integers(0, 99),
    ),
    max_size=24,
)


def list_history(script):
    oracle = SeqList()
    streams = {a: [] for a in ACTORS}
    for actor_i, kind, pos, val in script:
        actor = ACTORS[actor_i]
        if kind == "ins":
            op = oracle.insert_ctx(actor, pos % (len(oracle) + 1), val)
        else:
            if len(oracle) == 0:
                continue
            op = oracle.delete_ctx(pos % len(oracle))
        oracle.apply(op)
        streams[actor].append(op)
    return oracle, [s for s in streams.values() if s]


@settings(max_examples=150, deadline=None)
@given(list_script, st.data())
def test_list_convergence_under_interleaving(script, data):
    oracle, streams = list_history(script)
    replica = SeqList()
    for op in interleave(streams, data):
        replica.apply(op)
    assert canonical_bytes(replica) == canonical_bytes(oracle)
    # wire round-trip
    assert canonical_bytes(
        SeqList.from_obj(replica.to_obj())
    ) == canonical_bytes(oracle)


@settings(max_examples=80, deadline=None)
@given(list_script, st.data())
def test_list_merge_laws_and_cm_cv_agreement(script, data):
    oracle, streams = list_history(script)
    replicas = []
    for s in streams:
        r = SeqList()
        for op in s:
            r.apply(op)
        replicas.append(r)
    if not replicas:
        return
    merge_laws(replicas, SeqList)
    merged = SeqList()
    for r in replicas:
        merged.merge(r)
    assert canonical_bytes(merged) == canonical_bytes(oracle)


def test_list_sequential_editing_semantics():
    """Single-writer editing behaves like a plain list."""
    a = ACTORS[0]
    lst = SeqList()
    for i, ch in enumerate("hello"):
        lst.apply(lst.insert_ctx(a, i, ch))
    assert lst.read() == list("hello")
    lst.apply(lst.insert_ctx(a, 0, ">"))
    assert lst.read() == list(">hello")
    lst.apply(lst.delete_ctx(3))  # drop the first 'l'
    assert lst.read() == list(">helo")
    lst.apply(lst.insert_ctx(a, 5, "!"))
    assert lst.read() == list(">helo!")


def test_path_between_is_dense_and_ordered():
    lo = ()
    ids = []
    for _ in range(200):  # repeated head-insert exercises level growth
        lo_new = path_between((), ids[0] if ids else None)
        ids.insert(0, lo_new)
    assert ids == sorted(ids)
    assert len(set(ids)) == len(ids)
    mid = path_between(ids[3], ids[4])
    assert ids[3] < mid < ids[4]


# ---- GSet -----------------------------------------------------------------

gset_script = st.lists(
    st.tuples(st.integers(0, 3), st.integers(0, 9)), max_size=20
)


@settings(max_examples=100, deadline=None)
@given(gset_script, st.data())
def test_gset_laws(script, data):
    oracle = GSet()
    streams = {a: [] for a in ACTORS}
    for actor_i, member in script:
        op = oracle.insert_ctx(member)
        oracle.apply(op)
        streams[ACTORS[actor_i]].append(op)
    replica = GSet()
    for op in interleave(list(streams.values()), data):
        replica.apply(op)
    assert canonical_bytes(replica) == canonical_bytes(oracle)
    replicas = []
    for s in streams.values():
        r = GSet()
        for op in s:
            r.apply(op)
        replicas.append(r)
    merge_laws(replicas, GSet)
    assert canonical_bytes(GSet.from_obj(oracle.to_obj())) == canonical_bytes(oracle)


# ---- LWWReg ---------------------------------------------------------------

lww_script = st.lists(
    st.tuples(st.integers(0, 3), st.integers(0, 5), st.integers(0, 99)),
    max_size=20,
)


@settings(max_examples=100, deadline=None)
@given(lww_script, st.data())
def test_lwwreg_laws(script, data):
    oracle = LWWReg()
    ops = []
    for actor_i, ts, val in script:
        op = oracle.write(ts, ACTORS[actor_i], val)
        oracle.apply(op)
        ops.append(op)
    replica = LWWReg()
    for op in interleave([ops[::2], ops[1::2]], data):
        replica.apply(op)
    assert canonical_bytes(replica) == canonical_bytes(oracle)
    replicas = []
    for chunk in (ops[::3], ops[1::3], ops[2::3]):
        r = LWWReg()
        for op in chunk:
            r.apply(op)
        replicas.append(r)
    if replicas:
        merge_laws(replicas, LWWReg)
    assert canonical_bytes(LWWReg.from_obj(oracle.to_obj())) == canonical_bytes(oracle)


# ---- MerkleReg ------------------------------------------------------------


def test_merklereg_supersession_and_concurrency():
    a, b = MerkleReg(), MerkleReg()
    w1 = a.write_ctx("v1")
    a.apply(w1)
    b.apply(w1)
    # concurrent writes on top of v1
    wa = a.write_ctx("va")
    wb = b.write_ctx("vb")
    a.apply(wa)
    b.apply(wb)
    a.merge(b)
    b.merge(a)
    assert canonical_bytes(a) == canonical_bytes(b)
    assert sorted(a.read()) == ["va", "vb"]  # two heads
    # a citing write resolves both heads
    w2 = a.write_ctx("resolved")
    a.apply(w2)
    assert a.read() == ["resolved"]
    b.apply(w2)
    assert canonical_bytes(b) == canonical_bytes(a)


@settings(max_examples=60, deadline=None)
@given(st.lists(st.integers(0, 99), min_size=1, max_size=12), st.data())
def test_merklereg_laws(vals, data):
    oracle = MerkleReg()
    ops = []
    for v in vals:
        op = oracle.write_ctx(v)
        oracle.apply(op)
        ops.append(op)
    replica = MerkleReg()
    for op in interleave([ops[::2], ops[1::2]], data):
        replica.apply(op)
    assert canonical_bytes(replica) == canonical_bytes(oracle)
    r1, r2 = MerkleReg(), MerkleReg()
    for op in ops[::2]:
        r1.apply(op)
    for op in ops[1::2]:
        r2.apply(op)
    merge_laws([r1, r2], MerkleReg)
    assert canonical_bytes(
        MerkleReg.from_obj(oracle.to_obj())
    ) == canonical_bytes(oracle)


# ---- Core lifecycle per type ----------------------------------------------


def _opts(remote, adapter):
    return OpenOptions(
        storage=MemoryStorage(remote),
        cryptor=IdentityCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=adapter,
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=True,
    )


@pytest.mark.parametrize(
    "adapter_fn,builders,expect",
    [
        (
            gset_adapter,
            [lambda c, s, i=i: s.insert_ctx(i) for i in (3, 1, 2)],
            lambda s: s.read() == [1, 2, 3],
        ),
        (
            lwwreg_adapter,
            [
                lambda c, s: s.write(1, c.actor_id, "old"),
                lambda c, s: s.write(9, c.actor_id, "new"),
            ],
            lambda s: s.read() == "new",
        ),
        (
            merklereg_adapter,
            [lambda c, s: s.write_ctx("x")],
            lambda s: s.read() == ["x"],
        ),
        (
            list_adapter,
            [
                lambda c, s: s.insert_ctx(c.actor_id, 0, "b"),
                lambda c, s: s.insert_ctx(c.actor_id, 0, "a"),
            ],
            lambda s: s.read() == ["a", "b"],
        ),
    ],
    ids=["gset", "lwwreg", "merklereg", "list"],
)
def test_core_lifecycle_new_types(adapter_fn, builders, expect):
    async def go():
        remote = MemoryRemote()
        writer = await Core.open(_opts(remote, adapter_fn()))
        # derive-then-apply one op at a time: each derivation must see the
        # previous op applied (update() persists and folds the result)
        for build in builders:
            await writer.update(lambda s, b=build: b(writer, s))
        await writer.compact()
        reader = await Core.open(_opts(remote, adapter_fn()))
        await reader.read_remote()
        assert reader.with_state(expect)
        assert reader.with_state(canonical_bytes) == writer.with_state(
            canonical_bytes
        )

    asyncio.run(go())

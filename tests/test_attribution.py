"""Cycle attribution + gap report (ISSUE 11): exact stage math, nesting
guards, overlap proof, and the CLI golden on the committed BENCH_LOCAL
``--e2e-streaming`` record (the acceptance: the ratio and the dominant
stage are machine-printed, byte-stable)."""

import json
import pathlib

from crdt_enc_tpu.obs import attribution
from crdt_enc_tpu.tools import obs_report

DATA = pathlib.Path(__file__).parent / "data"
REPO = pathlib.Path(__file__).parent.parent


def _snap(spans):
    return {
        "spans": {k: {"count": 1, "seconds": v} for k, v in spans.items()},
        "counters": {},
        "gauges": {},
    }


def test_streaming_stage_math_exact():
    rep = attribution.attribute_cycle(
        _snap({
            "stream.decrypt": 2.0,
            "stream.decode": 3.0,
            # nested inside stream.decode — must NOT double count
            "session.decode": 2.9,
            "stream.reduce": 1.0,
            "stream.finish": 0.5,
        }),
        wall_s=5.0, ops=1000,
    )
    assert rep["pipeline"] == "streaming"
    assert rep["stages"]["decrypt"]["seconds"] == 2.0
    assert rep["stages"]["decode"]["seconds"] == 3.0
    assert rep["stages"]["decode"]["spans"] == {"stream.decode": 3.0}
    assert rep["stages"]["fold"]["seconds"] == 1.0
    assert rep["stages"]["scatter"]["seconds"] == 0.5
    assert rep["serialized_s"] == 6.5
    assert rep["overlap_x"] == 1.3  # 6.5 / 5.0 — the pipeline overlapped
    assert rep["critical_path"] == "decode"
    assert rep["gap"] == {
        "ops": 1000,
        "e2e_ops_per_sec": 200.0,
        "fold_marginal_ops_per_sec": 1000.0,
        "gap_x": 5.0,
        "dominant_stage": "decode",
    }


def test_alternative_spans_when_stream_absent():
    """A bulk (non-pipelined) run records ops.bulk_* instead of
    stream.* — the stage groups fall through to them."""
    rep = attribution.attribute_cycle(
        _snap({"ops.bulk_decrypt": 1.0, "ops.bulk_fold": 4.0,
               "compact.seal": 0.25, "compact.write": 0.25}),
        wall_s=6.0, ops=600,
    )
    assert rep["stages"]["decrypt"]["seconds"] == 1.0
    assert rep["stages"]["fold"]["seconds"] == 4.0
    assert rep["stages"]["seal"]["seconds"] == 0.5  # disjoint groups sum
    assert rep["critical_path"] == "fold"
    assert rep["gap"]["fold_marginal_ops_per_sec"] == 150.0
    assert rep["gap"]["gap_x"] == 1.5


def test_serve_pipeline_detection_and_wall_inference():
    rep = attribution.attribute_cycle(
        _snap({"serve.cycle": 2.0, "serve.decrypt": 0.5,
               "serve.fold": 0.2, "serve.seal": 1.0}),
        ops=400,
    )
    assert rep["pipeline"] == "serve"
    assert rep["wall_s"] == 2.0  # inferred from serve.cycle
    assert rep["critical_path"] == "seal"
    assert rep["gap"]["e2e_ops_per_sec"] == 200.0
    assert rep["gap"]["dominant_stage"] == "seal"


def test_no_ops_or_wall_degrades_gracefully():
    rep = attribution.attribute_cycle(_snap({"stream.decrypt": 1.0}))
    assert rep["wall_s"] is None
    assert "gap" not in rep and "overlap_x" not in rep
    assert rep["critical_path"] == "decrypt"
    out = attribution.format_attribution(rep)
    assert "critical path: decrypt" in out


def test_events_give_wall_and_overlap_proof():
    """chunk k+1's ingest starting inside chunk k's reduce = one
    overlapped chunk, and the wall comes from the event extent."""
    def ev(name, t0, t1, chunk):
        return {"name": name, "kind": "span", "t0": t0, "t1": t1,
                "meta": chunk, "tid": 1, "thread": "t"}

    events = [
        ev("stream.ingest", 0.0, 1.0, 0),
        ev("stream.reduce", 1.0, 2.0, 0),
        ev("stream.ingest", 1.5, 2.5, 1),  # overlaps chunk 0's reduce
        ev("stream.reduce", 2.5, 3.5, 1),
    ]
    rep = attribution.attribute_cycle(
        _snap({"stream.reduce": 2.0}), events=events, ops=100,
    )
    assert rep["wall_s"] == 3.5
    assert rep["overlapped_chunks"] == 1
    assert rep["gap"]["fold_marginal_ops_per_sec"] == 50.0


def test_from_record_bench_and_sink_shapes():
    bench = {
        "metric": "orset_e2e_streaming_ops_per_sec",
        "e2e_overlapped_s": 2.0,
        "shape": {"total_ops": 1000},
        "obs": _snap({"stream.decrypt": 1.5, "stream.reduce": 0.1}),
    }
    rep = attribution.from_record(bench)
    assert rep["gap"]["e2e_ops_per_sec"] == 500.0
    assert rep["gap"]["gap_x"] == 20.0
    assert rep["critical_path"] == "decrypt"

    sink_rec = {
        "schema": 2, "label": "compact", "ts": 1.0,
        **_snap({"serve.cycle": 1.0, "serve.fold": 0.5}),
        "counters": {"serve_rows_folded": 50},
    }
    rep = attribution.from_record(sink_rec)
    assert rep["pipeline"] == "serve"
    assert rep["gap"]["ops"] == 50
    assert rep["gap"]["e2e_ops_per_sec"] == 50.0


# ---- the CLI + the committed-record golden --------------------------------


def test_cli_gap_golden_on_committed_streaming_record(capsys):
    """The acceptance gate: `obs_report gap` on the committed
    BENCH_LOCAL --e2e-streaming record prints the e2e-vs-fold-marginal
    ratio and names the dominant stage, byte-identical to the
    committed golden."""
    assert obs_report.main([
        "gap", str(REPO / "BENCH_LOCAL.jsonl"),
        "--metric", "orset_e2e_streaming_ops_per_sec",
    ]) == 0
    out = capsys.readouterr().out
    assert out == (DATA / "obs_gap_golden.txt").read_text()
    # the two headline facts, asserted independently of the rendering —
    # the ISSUE-13 witness: decode is no longer the dominant stage (the
    # PR-11 record read 10.65x decode-dominant; the encrypted-ingest
    # work moved the record to 7.36x with decrypt ahead)
    assert "= 7.36x" in out
    assert "dominant stage: decrypt" in out


def test_cli_gap_serve_record_and_json(capsys):
    assert obs_report.main([
        "gap", str(REPO / "BENCH_LOCAL.jsonl"),
        "--metric", "orset_multitenant_agg_ops_per_sec", "--json",
    ]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["pipeline"] == "serve"
    assert rep["gap"]["dominant_stage"] == rep["critical_path"]


def test_cli_gap_no_attributable_records(tmp_path, capsys):
    p = tmp_path / "empty.jsonl"
    p.write_text(json.dumps({"metric": "x", "value": 1.0}) + "\n")
    assert obs_report.main(["gap", str(p)]) == 2
    assert "no attributable records" in capsys.readouterr().err


def test_cli_gap_rejects_unreadable_schema(tmp_path, capsys):
    """gap shares slo/trend's schema contract: refuse a future sink
    format loudly instead of misattributing it."""
    p = tmp_path / "future.jsonl"
    p.write_text(json.dumps({"schema": 99, "label": "compact",
                             "spans": {}}) + "\n")
    assert obs_report.main(["gap", str(p)]) == 2
    assert "schema" in capsys.readouterr().err

"""Freshness SLOs (ISSUE 11): spec resolution, live gauges, exact
window burn accounting, FoldService cycle burn, the obs_report slo CLI,
and the fleet report's SLO column."""

import json
import pathlib

import pytest

from crdt_enc_tpu.obs import fleet, record, slo
from crdt_enc_tpu.tools import obs_report

DATA = pathlib.Path(__file__).parent / "data"


@pytest.fixture(autouse=True)
def _clean(monkeypatch):
    for var in (slo.ENV_FRESHNESS, slo.ENV_SEAL, slo.ENV_OBJECTIVE):
        monkeypatch.delenv(var, raising=False)
    record.reset()
    yield
    record.reset()


def _rep(wm_lag):
    return {"divergence": {"watermark_lag": wm_lag}}


def _freshness_rec(ts, wm_lag):
    return {"schema": 2, "label": "compact", "ts": ts,
            "spans": {}, "counters": {}, "gauges": {},
            "replication": {"divergence": {"watermark_lag": wm_lag}}}


def _cycle_rec(ts, attempts, violations):
    return {"schema": 2, "label": "serve_cycle", "ts": ts,
            "spans": {}, "counters": {}, "gauges": {},
            "meta": {"slo": {"attempts": attempts,
                             "violations": violations}}}


# ---- specs ----------------------------------------------------------------


def test_spec_defaults_and_env_overrides(monkeypatch):
    f = slo.freshness_spec()
    assert (f.target, f.objective) == (64.0, 0.99)
    assert slo.seal_latency_spec().target == 2.0
    monkeypatch.setenv(slo.ENV_FRESHNESS, "8")
    monkeypatch.setenv(slo.ENV_SEAL, "0.5")
    monkeypatch.setenv(slo.ENV_OBJECTIVE, "0.9")
    assert slo.freshness_spec().target == 8.0
    assert slo.freshness_spec().objective == 0.9
    assert slo.seal_latency_spec().target == 0.5
    # garbage / out-of-range values fall back, never raise
    monkeypatch.setenv(slo.ENV_FRESHNESS, "banana")
    monkeypatch.setenv(slo.ENV_OBJECTIVE, "7")
    assert slo.freshness_spec().target == 64.0
    assert slo.freshness_spec().objective == 0.99
    # a 1.0 objective cannot zero-divide the budget
    assert slo.SloSpec("x", "i", 1.0, objective=1.0).budget > 0


def test_sample_freshness_gauges(monkeypatch):
    monkeypatch.setenv(slo.ENV_FRESHNESS, "10")
    assert slo.sample_freshness(_rep(10)) is True
    g = record.snapshot()["gauges"]
    assert g["repl_slo_freshness_ok"] == 1.0
    assert g["repl_slo_freshness_target"] == 10.0
    assert slo.sample_freshness(_rep(11)) is False
    assert record.snapshot()["gauges"]["repl_slo_freshness_ok"] == 0.0


# ---- burn accounting ------------------------------------------------------


def test_burn_report_windows_exact(monkeypatch):
    monkeypatch.setenv(slo.ENV_FRESHNESS, "5")
    # window 0 (t=0..99): 4 samples, 1 violation; window 1: empty;
    # window 2 (t=200..): 2 samples, 2 violations
    records = [
        _freshness_rec(1000.0, 0),
        _freshness_rec(1010.0, 5),    # at target = ok
        _freshness_rec(1020.0, 6),    # violation
        _freshness_rec(1099.0, 1),
        _freshness_rec(1200.0, 50),   # violation
        _freshness_rec(1250.0, 500),  # violation
    ]
    rep = slo.burn_report(records, window_s=100.0)
    [fresh, seal] = rep["specs"]
    assert fresh["name"] == "freshness"
    assert fresh["samples"] == 6 and fresh["violations"] == 3
    assert fresh["bad_fraction"] == 0.5
    assert fresh["budget_burn"] == 50.0  # 0.5 / 0.01
    assert fresh["windows"] == [
        {"window": 0, "start_s": 0.0, "samples": 4, "violations": 1,
         "burn_rate": 25.0},
        {"window": 2, "start_s": 200.0, "samples": 2, "violations": 2,
         "burn_rate": 100.0},
    ]
    assert fresh["worst_window_burn"] == 100.0
    # no FoldService ran: zero seal-latency samples, not compliance
    assert seal["name"] == "seal_latency"
    assert seal["samples"] == 0 and seal["windows"] == []
    out = slo.format_burn(rep)
    assert "budget burn 50.00x" in out
    assert "(no samples)" in out


def test_burn_report_seal_latency_from_cycle_records():
    records = [
        _cycle_rec(0.0, 10, 0),
        _cycle_rec(10.0, 10, 2),
    ]
    rep = slo.burn_report(records, window_s=300.0)
    seal = rep["specs"][1]
    assert seal["samples"] == 20 and seal["violations"] == 2
    assert seal["bad_fraction"] == 0.1
    assert seal["budget_burn"] == 10.0
    assert seal["windows"] == [
        {"window": 0, "start_s": 0.0, "samples": 20, "violations": 2,
         "burn_rate": 10.0},
    ]


def test_cycle_burn(monkeypatch):
    class R:
        def __init__(self, sealed, latency_s):
            self.sealed = sealed
            self.latency_s = latency_s
            self.error = None

    monkeypatch.setenv(slo.ENV_SEAL, "1.0")
    burn = slo.cycle_burn([R(True, 0.5), R(True, 1.5), R(False, 9.0)])
    assert burn["tenants"] == 3 and burn["sealed"] == 2
    assert burn["attempts"] == 2  # the skipped tenant was no attempt
    assert burn["violations"] == 1
    assert burn["burn_rate"] == 50.0  # (1/2) / 0.01
    assert slo.cycle_burn([])["burn_rate"] == 0.0


def test_cycle_burn_errored_tenants_are_violations(monkeypatch):
    """A seal that never happened is infinitely late: a total outage
    (every tenant errors) must burn at the maximum rate, never render
    as a green zero-sealed/zero-violation cycle."""
    class R:
        def __init__(self, sealed=False, latency_s=0.0, error=None):
            self.sealed = sealed
            self.latency_s = latency_s
            self.error = error

    monkeypatch.setenv(slo.ENV_SEAL, "1.0")
    burn = slo.cycle_burn([R(error="boom"), R(error="boom")])
    assert burn["sealed"] == 0 and burn["errors"] == 2
    assert burn["attempts"] == 2 and burn["violations"] == 2
    assert burn["burn_rate"] == 100.0  # (2/2) / 0.01 — max burn
    # mixed: one fast seal, one error → half the attempts violated
    burn = slo.cycle_burn([R(sealed=True, latency_s=0.1),
                           R(error="boom")])
    assert burn["attempts"] == 2 and burn["violations"] == 1
    assert burn["burn_rate"] == 50.0


# ---- CLI ------------------------------------------------------------------


def test_cli_slo_and_fail_on_burn(tmp_path, capsys, monkeypatch):
    monkeypatch.setenv(slo.ENV_FRESHNESS, "5")
    p = tmp_path / "run.jsonl"
    p.write_text("".join(
        json.dumps(r) + "\n" for r in
        [_freshness_rec(0.0, 0), _freshness_rec(1.0, 100)]
    ))
    assert obs_report.main(["slo", str(p)]) == 0
    out = capsys.readouterr().out
    assert "freshness: target <= 5" in out
    assert "budget burn 50.00x" in out
    # --fail-on-burn turns the over-budget spec into exit 1
    assert obs_report.main(["slo", str(p), "--fail-on-burn"]) == 1
    assert "freshness" in capsys.readouterr().err
    # --json round-trips
    assert obs_report.main(["slo", str(p), "--json"]) == 0
    rep = json.loads(capsys.readouterr().out)
    assert rep["specs"][0]["budget_burn"] == 50.0
    # unreadable schema fails loudly with exit 2
    bad = tmp_path / "bad.jsonl"
    bad.write_text(json.dumps({"schema": 99, "label": "x"}) + "\n")
    assert obs_report.main(["slo", str(bad)]) == 2


# ---- fleet SLO column -----------------------------------------------------


def _dev_record(actor_hex, wm_lag, ts=100.0):
    return {
        "schema": 2, "label": "compact", "ts": ts,
        "spans": {}, "counters": {}, "gauges": {},
        "replication": {
            "actor": actor_hex,
            "remote_id": "99" * 32,
            "local_clock": {actor_hex: 1},
            "union_clock": {actor_hex: 1},
            "watermark": {}, "matrix": {},
            "backlog": {"files": 0, "bytes": 0, "per_actor": {}},
            "divergence": {"actors_behind": 0, "version_lag": 0,
                           "watermark_lag": wm_lag, "known_replicas": 1},
            "checkpoint": {"enabled": False, "sealed": False,
                           "staleness_versions": 0},
        },
    }


def test_fleet_report_slo_column(tmp_path, monkeypatch):
    monkeypatch.setenv(slo.ENV_FRESHNESS, "10")
    paths = []
    for i, lag in enumerate((0, 99)):
        p = tmp_path / f"d{i}.jsonl"
        p.write_text(json.dumps(_dev_record(f"{i:02x}" * 16, lag)) + "\n")
        paths.append(str(p))
    report = fleet.fleet_report(fleet.device_summaries(paths))
    [r] = report["remotes"]
    assert r["slo"] == {
        "freshness_target": 10.0, "devices_ok": 1, "devices_burning": 1,
    }
    assert [d["slo_ok"] for d in r["devices"]] == [True, False]
    out = fleet.format_fleet(report)
    assert "slo freshness (lag<=10): 1 ok, 1 burning" in out
    assert "slo=ok" in out and "slo=BURN" in out

"""Wire-substrate tests: VersionBytes raw/msgpack forms and the Buf contract.

Mirrors the reference's only test file
(/root/reference/crdt-enc/tests/version_box_buf.rs:9-140) and its
ensure_versions doctests (version_bytes.rs:52-71, 151-170), then goes further
with round-trip and canonical-codec properties.
"""

import uuid

import msgpack
import pytest

from crdt_enc_tpu.utils import (
    VERSION_LEN,
    DeserializeError,
    VersionBytes,
    VersionBytesBuf,
    VersionError,
    codec,
)

V1 = uuid.UUID("00000000-0000-0000-0000-0000000000aa").bytes
V2 = uuid.UUID("00000000-0000-0000-0000-0000000000bb").bytes


def test_raw_roundtrip():
    vb = VersionBytes(V1, b"hello world")
    raw = vb.serialize()
    assert raw == V1 + b"hello world"
    assert VersionBytes.deserialize(raw) == vb


def test_raw_too_short():
    with pytest.raises(DeserializeError):
        VersionBytes.deserialize(b"short")


def test_raw_empty_content():
    vb = VersionBytes.deserialize(V1)
    assert vb.version == V1 and vb.content == b""


def test_msgpack_form_roundtrip():
    vb = VersionBytes(V2, b"\x00\x01payload")
    packed = codec.pack(vb.to_obj())
    obj = codec.unpack(packed)
    assert VersionBytes.from_obj(obj) == vb
    # 2-element array of bins, like the reference's serde tuple form.
    assert msgpack.unpackb(packed) == [V2, b"\x00\x01payload"]


def test_ensure_version():
    vb = VersionBytes(V1, b"x")
    assert vb.ensure_version(V1) is vb
    with pytest.raises(VersionError):
        vb.ensure_version(V2)


def test_ensure_versions_accept_reject():
    vb = VersionBytes(V1, b"x")
    assert vb.ensure_versions({V1, V2}) is vb
    with pytest.raises(VersionError):
        vb.ensure_versions({V2})
    with pytest.raises(VersionError):
        vb.ensure_versions(set())


# ---- Buf contract (reference version_box_buf.rs) -------------------------


def test_buf_simple():
    # reference `simple` (:9-33): sequential chunk/advance through both parts.
    buf = VersionBytesBuf(V1, b"content!")
    assert buf.remaining() == VERSION_LEN + 8
    assert bytes(buf.chunk()) == V1
    buf.advance(VERSION_LEN)
    assert buf.remaining() == 8
    assert bytes(buf.chunk()) == b"content!"
    buf.advance(8)
    assert buf.remaining() == 0


def test_buf_unaligned_advance():
    # reference `unaligned_advance` (:36-63): advances straddling the
    # 16-byte version/content boundary.
    buf = VersionBytesBuf(V1, b"abcdef")
    buf.advance(10)
    assert bytes(buf.chunk()) == V1[10:]
    buf.advance(6)  # exactly at the boundary
    assert bytes(buf.chunk()) == b"abcdef"
    buf2 = VersionBytesBuf(V1, b"abcdef")
    buf2.advance(18)  # 2 bytes past the boundary
    assert bytes(buf2.chunk()) == b"cdef"
    assert buf2.remaining() == 4


def test_buf_out_of_bounds_advance():
    # reference `out_of_bounds_advance` (:66-70): over-advance must raise.
    buf = VersionBytesBuf(V1, b"abc")
    with pytest.raises(IndexError):
        buf.advance(VERSION_LEN + 4)


def test_buf_vectored():
    # reference `vectored` (:73-140): chunk enumeration for writev.
    buf = VersionBytesBuf(V1, b"xyz")
    chunks = buf.chunks_vectored()
    assert [bytes(c) for c in chunks] == [V1, b"xyz"]
    buf.advance(VERSION_LEN + 1)
    assert [bytes(c) for c in buf.chunks_vectored()] == [b"yz"]
    buf.advance(2)
    assert buf.chunks_vectored() == []
    # limit of 1 yields only the first chunk
    buf2 = VersionBytesBuf(V1, b"xyz")
    assert [bytes(c) for c in buf2.chunks_vectored(limit=1)] == [V1]


def test_buf_read_all_equals_serialize():
    vb = VersionBytes(V2, b"roundtrip")
    assert vb.buf().read_all() == vb.serialize()


def test_buf_vectored_through_real_writev(tmp_path):
    # the point of chunks_vectored: version‖content hit the kernel in one
    # vectored syscall with zero concatenation
    import os

    vb = VersionBytes(V1, b"payload-bytes")
    fd = os.open(str(tmp_path / "out"), os.O_WRONLY | os.O_CREAT, 0o644)
    try:
        written = os.writev(fd, vb.buf().chunks_vectored())
    finally:
        os.close(fd)
    assert written == VERSION_LEN + len(b"payload-bytes")
    assert (tmp_path / "out").read_bytes() == vb.serialize()


def test_canonical_pack_sorts_map_keys():
    a = codec.pack({b"b": 1, b"a": 2})
    b = codec.pack({b"a": 2, b"b": 1})
    assert a == b


def test_from_obj_rejects_malformed():
    with pytest.raises(DeserializeError):
        VersionBytes.from_obj([16, 3])
    with pytest.raises(DeserializeError):
        VersionBytes.from_obj(["x", "y"])
    with pytest.raises(DeserializeError):
        VersionBytes.from_obj([b"short", b"content"])


def test_buf_negative_advance():
    buf = VersionBytesBuf(V1, b"abc")
    with pytest.raises(IndexError):
        buf.advance(-5)


def test_canonical_pack_tuple_keys():
    packed = codec.pack({(1, 2): 3, (1, 1): 4})
    assert codec.unpack(packed) == {(1, 1): 4, (1, 2): 3}

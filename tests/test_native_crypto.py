"""Native AEAD validation.

The IETF ChaCha20-Poly1305 core is cross-checked against the `cryptography`
wheel (an independent implementation) over randomized keys/nonces/payloads
— transitively validating the ChaCha20 block function and Poly1305 used by
the XChaCha construction.  HChaCha20 and XChaCha then get self-consistency,
tamper, wrong-key, and wire-format tests, plus the public draft test vector
for HChaCha20.
"""

import secrets

import pytest

from crdt_enc_tpu import native
from crdt_enc_tpu.backends.xchacha import (
    AeadError,
    decrypt_blob,
    encrypt_blob,
)
from crdt_enc_tpu.utils import VersionBytes
from crdt_enc_tpu.utils.versions import XCHACHA_DATA_VERSION_1


def _ietf_encrypt(key, nonce, aad, pt):
    lib = native.load()
    kp, _1 = native.in_ptr(key)
    np_, _2 = native.in_ptr(nonce)
    ap, _3 = native.in_ptr(aad)
    pp, _4 = native.in_ptr(pt)
    op, out = native.out_buf(len(pt) + 16)
    lib.chacha20poly1305_encrypt(kp, np_, ap, len(aad), pp, len(pt), op)
    return out.tobytes()


def _ietf_decrypt(key, nonce, aad, ct):
    lib = native.load()
    kp, _1 = native.in_ptr(key)
    np_, _2 = native.in_ptr(nonce)
    ap, _3 = native.in_ptr(aad)
    cp, _4 = native.in_ptr(ct)
    op, out = native.out_buf(max(len(ct) - 16, 0))
    rc = lib.chacha20poly1305_decrypt(kp, np_, ap, len(aad), cp, len(ct), op)
    return out.tobytes() if rc == 0 else None


def test_ietf_matches_cryptography_wheel():
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

    # sizes straddle the 8-block SIMD lane boundary (512 bytes): the lane
    # path must match the oracle, not just roundtrip against itself —
    # a symmetric lane/counter permutation would pass a self-roundtrip
    sizes = [0, 1, 63, 64, 300, 511, 512, 513, 1024, 4096, 100_000]
    for trial in range(20):
        key = secrets.token_bytes(32)
        nonce = secrets.token_bytes(12)
        aad = secrets.token_bytes(trial % 7 * 5)
        pt = secrets.token_bytes(sizes[trial % len(sizes)] + trial * 37 % 301)
        oracle = ChaCha20Poly1305(key).encrypt(nonce, pt, aad or None)
        ours = _ietf_encrypt(key, nonce, aad, pt)
        assert ours == oracle
        # and our decrypt opens the oracle's ciphertext
        assert _ietf_decrypt(key, nonce, aad, oracle) == pt


def test_ietf_empty_plaintext_and_aad():
    pytest.importorskip("cryptography")
    from cryptography.hazmat.primitives.ciphers.aead import ChaCha20Poly1305

    key, nonce = secrets.token_bytes(32), secrets.token_bytes(12)
    assert _ietf_encrypt(key, nonce, b"", b"") == ChaCha20Poly1305(key).encrypt(
        nonce, b"", None
    )


def _hchacha_oracle(key: bytes, nonce16: bytes) -> bytes:
    """Independent HChaCha20 oracle: the cryptography wheel's ChaCha20 block
    (which includes the final state addition) minus the known initial state
    — words 0-3 and 12-15 of the bare core, per draft-irtf-cfrg-xchacha §2.2."""
    import struct

    from cryptography.hazmat.primitives.ciphers import Cipher, algorithms

    c = struct.unpack("<I", nonce16[:4])[0]
    full = struct.pack("<I", c) + nonce16[4:]
    ks = (
        Cipher(algorithms.ChaCha20(key, full), mode=None)
        .encryptor()
        .update(bytes(64))
    )
    words = struct.unpack("<16I", ks)
    sigma = (0x61707865, 0x3320646E, 0x79622D32, 0x6B206574)
    init = (
        list(sigma)
        + list(struct.unpack("<8I", key))
        + [c]
        + list(struct.unpack("<3I", nonce16[4:]))
    )
    core = [(w - i) & 0xFFFFFFFF for w, i in zip(words, init)]
    return struct.pack("<4I", *core[0:4]) + struct.pack("<4I", *core[12:16])


def _hchacha_ours(key: bytes, nonce16: bytes) -> bytes:
    lib = native.load()
    kp, _1 = native.in_ptr(key)
    np_, _2 = native.in_ptr(nonce16)
    op, out = native.out_buf(32)
    lib.hchacha20(kp, np_, op)
    return out.tobytes()


def test_hchacha20_draft_vector():
    pytest.importorskip("cryptography")
    # draft-irtf-cfrg-xchacha §2.2.1 inputs; expectation pinned against the
    # independent oracle above (which also validates the oracle derivation:
    # the first 16 output bytes are the draft's well-known 82413b42… prefix)
    key = bytes.fromhex(
        "000102030405060708090a0b0c0d0e0f101112131415161718191a1b1c1d1e1f"
    )
    nonce = bytes.fromhex("000000090000004a0000000031415927")
    ours = _hchacha_ours(key, nonce)
    assert ours == _hchacha_oracle(key, nonce)
    assert ours[:16].hex() == "82413b4227b27bfed30e42508a877d73"


def test_hchacha20_randomized_vs_oracle():
    pytest.importorskip("cryptography")
    for _ in range(10):
        key, nonce = secrets.token_bytes(32), secrets.token_bytes(16)
        assert _hchacha_ours(key, nonce) == _hchacha_oracle(key, nonce)


def test_xchacha_roundtrip_and_envelope():
    key = secrets.token_bytes(32)
    blob = encrypt_blob(key, b"hello crdt")
    vb = VersionBytes.deserialize(blob)
    assert vb.version == XCHACHA_DATA_VERSION_1  # envelope version tag
    assert decrypt_blob(key, blob) == b"hello crdt"
    # nonces are fresh per seal: same plaintext, different ciphertext
    assert encrypt_blob(key, b"hello crdt") != blob


def test_xchacha_tamper_detected():
    key = secrets.token_bytes(32)
    blob = bytearray(encrypt_blob(key, b"payload" * 10))
    blob[-1] ^= 0x01
    with pytest.raises(AeadError):
        decrypt_blob(key, bytes(blob))


def test_xchacha_wrong_key_detected():
    blob = encrypt_blob(secrets.token_bytes(32), b"secret")
    with pytest.raises(AeadError):
        decrypt_blob(secrets.token_bytes(32), blob)


def test_xchacha_large_payload():
    key = secrets.token_bytes(32)
    pt = secrets.token_bytes(1 << 20)  # 1 MiB
    assert decrypt_blob(key, encrypt_blob(key, pt)) == pt


def test_batch_decrypt():
    import numpy as np

    lib = native.load()
    key = secrets.token_bytes(32)
    n = 50
    pts, nonces, cts = [], [], []
    from crdt_enc_tpu.utils import codec

    for i in range(n):
        pt = secrets.token_bytes(10 + i * 3)
        blob = encrypt_blob(key, pt)
        nonce, ct = codec.unpack(VersionBytes.deserialize(blob).content)
        pts.append(pt)
        nonces.append(bytes(nonce))
        cts.append(bytes(ct))
    offsets = np.zeros(n + 1, np.uint64)
    for i, ct in enumerate(cts):
        offsets[i + 1] = offsets[i] + len(ct)
    out_offsets = np.zeros(n, np.uint64)
    total_out = 0
    for i, ct in enumerate(cts):
        out_offsets[i] = total_out
        total_out += len(ct) - 16
    flat_ct = b"".join(cts)
    flat_nonce = b"".join(nonces)
    kp, _1 = native.in_ptr(key)
    np1, _2 = native.in_ptr(flat_nonce)
    cp, _3 = native.in_ptr(flat_ct)
    op, out = native.out_buf(total_out)
    ok_p, ok = native.out_buf(n)
    import ctypes

    failures = lib.xchacha20poly1305_decrypt_batch(
        kp,
        np1,
        cp,
        offsets.ctypes.data_as(native.u64p),
        n,
        op,
        out_offsets.ctypes.data_as(native.u64p),
        ok_p,
    )
    assert failures == 0 and bool(ok.all())
    for i, pt in enumerate(pts):
        start = int(out_offsets[i])
        assert out[start : start + len(pt)].tobytes() == pt


# ---- wheel-free oracle -----------------------------------------------------
# The tests above need the `cryptography` wheel; boxes without it still
# must not ship an unvalidated SIMD keystream (the 8/16-lane transpose
# paths are exactly where a compiler/builtin-shim slip would hide, and a
# symmetric permutation error survives roundtrip tests).  This oracle is
# ~40 lines of pure Python — slow, unconditional, independent.


def _chacha_block_py(key: bytes, counter: int, nonce: bytes) -> bytes:
    import struct

    def rotl(x, n):
        return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF

    def qr(s, a, b, c, d):
        s[a] = (s[a] + s[b]) & 0xFFFFFFFF; s[d] = rotl(s[d] ^ s[a], 16)
        s[c] = (s[c] + s[d]) & 0xFFFFFFFF; s[b] = rotl(s[b] ^ s[c], 12)
        s[a] = (s[a] + s[b]) & 0xFFFFFFFF; s[d] = rotl(s[d] ^ s[a], 8)
        s[c] = (s[c] + s[d]) & 0xFFFFFFFF; s[b] = rotl(s[b] ^ s[c], 7)

    st = (
        list(struct.unpack("<4I", b"expand 32-byte k"))
        + list(struct.unpack("<8I", key))
        + [counter]
        + list(struct.unpack("<3I", nonce))
    )
    w = st[:]
    for _ in range(10):
        qr(w, 0, 4, 8, 12); qr(w, 1, 5, 9, 13)
        qr(w, 2, 6, 10, 14); qr(w, 3, 7, 11, 15)
        qr(w, 0, 5, 10, 15); qr(w, 1, 6, 11, 12)
        qr(w, 2, 7, 8, 13); qr(w, 3, 4, 9, 14)
    return struct.pack(
        "<16I", *((a + b) & 0xFFFFFFFF for a, b in zip(w, st))
    )


def _poly1305_py(otk: bytes, msg: bytes) -> bytes:
    r = int.from_bytes(otk[:16], "little") & 0x0FFFFFFC0FFFFFFC0FFFFFFC0FFFFFFF
    s = int.from_bytes(otk[16:], "little")
    p = (1 << 130) - 5
    acc = 0
    for i in range(0, len(msg), 16):
        acc = (acc + int.from_bytes(msg[i : i + 16] + b"\x01", "little")) * r % p
    return ((acc + s) & ((1 << 128) - 1)).to_bytes(16, "little")


def _aead_py(key: bytes, nonce: bytes, aad: bytes, pt: bytes) -> bytes:
    ct = bytes(
        x ^ y
        for i in range(0, len(pt), 64)
        for x, y in zip(
            pt[i : i + 64], _chacha_block_py(key, 1 + i // 64, nonce)
        )
    )
    otk = _chacha_block_py(key, 0, nonce)[:32]

    def pad16(b):
        return b + bytes(-len(b) % 16)

    mac_data = (
        pad16(aad) + pad16(ct)
        + len(aad).to_bytes(8, "little") + len(ct).to_bytes(8, "little")
    )
    return ct + _poly1305_py(otk, mac_data)


def test_ietf_matches_pure_python_reference():
    """Wheel-free AEAD oracle across sizes straddling the scalar, 4-lane
    (256B groups), 8-lane (512B) and 16-lane (1KB) keystream paths."""
    sizes = [0, 1, 63, 64, 255, 256, 300, 511, 512, 513, 1024, 2048, 4096,
             8192]
    for trial, size in enumerate(sizes):
        key = secrets.token_bytes(32)
        nonce = secrets.token_bytes(12)
        aad = secrets.token_bytes(trial % 5 * 7)
        pt = secrets.token_bytes(size)
        oracle = _aead_py(key, nonce, aad, pt)
        assert _ietf_encrypt(key, nonce, aad, pt) == oracle, size
        assert _ietf_decrypt(key, nonce, aad, oracle) == pt, size


# ---- batched/vectorized AEAD vs the pure-Python XChaCha oracle -------------
# The SIMD batch engine (lane-generic ChaCha phases + batched Poly1305
# pass) now serves BOTH the EncBox scatter path and the raw
# xchacha20poly1305_decrypt_batch(_mt) FFI surface.  Every blob below is
# independently sealed by the pure-Python oracle — a lane permutation,
# counter slip, or tag-phase error cannot survive these.


def _hchacha_py(key: bytes, nonce16: bytes) -> bytes:
    """Pure-Python HChaCha20 (draft §2.2): the ChaCha rounds with NO
    final state addition; subkey = words 0..3 ‖ 12..15."""
    import struct

    def rotl(x, n):
        return ((x << n) | (x >> (32 - n))) & 0xFFFFFFFF

    def qr(s, a, b, c, d):
        s[a] = (s[a] + s[b]) & 0xFFFFFFFF; s[d] = rotl(s[d] ^ s[a], 16)
        s[c] = (s[c] + s[d]) & 0xFFFFFFFF; s[b] = rotl(s[b] ^ s[c], 12)
        s[a] = (s[a] + s[b]) & 0xFFFFFFFF; s[d] = rotl(s[d] ^ s[a], 8)
        s[c] = (s[c] + s[d]) & 0xFFFFFFFF; s[b] = rotl(s[b] ^ s[c], 7)

    w = (
        list(struct.unpack("<4I", b"expand 32-byte k"))
        + list(struct.unpack("<8I", key))
        + list(struct.unpack("<4I", nonce16))
    )
    for _ in range(10):
        qr(w, 0, 4, 8, 12); qr(w, 1, 5, 9, 13)
        qr(w, 2, 6, 10, 14); qr(w, 3, 7, 11, 15)
        qr(w, 0, 5, 10, 15); qr(w, 1, 6, 11, 12)
        qr(w, 2, 7, 8, 13); qr(w, 3, 4, 9, 14)
    return struct.pack("<4I", *w[0:4]) + struct.pack("<4I", *w[12:16])


def _xchacha_seal_py(key: bytes, nonce24: bytes, pt: bytes) -> bytes:
    """Pure-Python XChaCha20-Poly1305 seal → ct ‖ tag (no envelope)."""
    subkey = _hchacha_py(key, nonce24[:16])
    nonce12 = bytes(4) + nonce24[16:]
    return _aead_py(subkey, nonce12, b"", pt)


def _run_batch_mt(key, nonces, cts, n_threads):
    import ctypes

    import numpy as np

    lib = native.load()
    n = len(cts)
    offsets = np.zeros(n + 1, np.uint64)
    out_offsets = np.zeros(n, np.uint64)
    total_out = 0
    for i, ct in enumerate(cts):
        offsets[i + 1] = offsets[i] + len(ct)
        out_offsets[i] = total_out
        total_out += len(ct) - 16
    kp, _1 = native.in_ptr(key)
    np1, _2 = native.in_ptr(b"".join(nonces))
    cp, _3 = native.in_ptr(b"".join(cts))
    op, out = native.out_buf(total_out)
    ok_p, ok = native.out_buf(n)
    failures = lib.xchacha20poly1305_decrypt_batch_mt(
        kp, np1, cp, offsets.ctypes.data_as(native.u64p), n, op,
        out_offsets.ctypes.data_as(native.u64p), ok_p,
        ctypes.c_int(n_threads),
    )
    return failures, ok, out, out_offsets


def test_batch_mt_matches_pure_python_oracle_random_shapes():
    """Random lengths / alignments / batch sizes: every blob sealed by
    the wheel-free Python oracle must open byte-identically through the
    SIMD batch engine — including batch sizes straddling the ≥32
    batched-kernel threshold and lane-partial tails."""
    import random

    rng = random.Random(1337)
    for n in (1, 2, 3, 15, 16, 17, 31, 32, 33, 50, 100):
        key = secrets.token_bytes(32)
        pts, nonces, cts = [], [], []
        for i in range(n):
            # lengths hit empty, sub-block, block-boundary ±1, multi-
            # block, and 16-byte-alignment straddles
            ln = rng.choice(
                [0, 1, 15, 16, 17, 31, 47, 63, 64, 65, 127, 300, 1025]
            )
            pt = secrets.token_bytes(ln)
            nonce = secrets.token_bytes(24)
            pts.append(pt)
            nonces.append(nonce)
            cts.append(_xchacha_seal_py(key, nonce, pt))
        failures, ok, out, out_offsets = _run_batch_mt(key, nonces, cts, 1)
        assert failures == 0 and bool(ok.all()), n
        for i, pt in enumerate(pts):
            lo = int(out_offsets[i])
            assert out[lo : lo + len(pt)].tobytes() == pt, (n, i)


def test_batch_mt_tamper_rejected_per_stripe():
    """Tampered blobs scattered through a batch: exactly those blobs
    flag failed (per-stripe rejection), the rest open, and — the
    verify-then-decrypt order — no plaintext is written for a failed
    blob."""
    key = secrets.token_bytes(32)
    n = 64
    pts, nonces, cts = [], [], []
    for i in range(n):
        pt = secrets.token_bytes(40 + i)
        nonce = secrets.token_bytes(24)
        pts.append(pt)
        nonces.append(nonce)
        cts.append(_xchacha_seal_py(key, nonce, pt))
    bad = {3, 17, 18, 40, 63}
    for i in bad:
        blob = bytearray(cts[i])
        blob[i % len(blob)] ^= 0x40
        cts[i] = bytes(blob)
    failures, ok, out, out_offsets = _run_batch_mt(key, nonces, cts, 2)
    assert failures == len(bad)
    for i in range(n):
        lo = int(out_offsets[i])
        got = out[lo : lo + len(pts[i])].tobytes()
        if i in bad:
            assert not ok[i]
            # out_buf is uninitialized memory, but it must NOT contain
            # the decrypted plaintext of a tamper-rejected blob
            assert got != pts[i]
        else:
            assert ok[i] and got == pts[i]


@pytest.mark.parametrize("n_threads", [0, 1, 3, 100])
def test_batch_mt_thread_count_edges(n_threads):
    """n_threads 0 (engine floor), 1, small, and > blob count must all
    produce identical bytes and failure accounting."""
    key = secrets.token_bytes(32)
    n = 7
    pts, nonces, cts = [], [], []
    for i in range(n):
        pt = secrets.token_bytes(33 * i)
        nonce = secrets.token_bytes(24)
        pts.append(pt)
        nonces.append(nonce)
        cts.append(_xchacha_seal_py(key, nonce, pt))
    failures, ok, out, out_offsets = _run_batch_mt(key, nonces, cts, n_threads)
    assert failures == 0 and bool(ok.all())
    for i, pt in enumerate(pts):
        lo = int(out_offsets[i])
        assert out[lo : lo + len(pt)].tobytes() == pt


def test_simd_lane_dispatch_exported():
    """The resolved SIMD width is visible (16/8/4) — a diagnostics hook
    and a canary for the runtime dispatcher itself."""
    import ctypes

    lib = native.load()
    lib.crdt_simd_lanes.argtypes = []
    lib.crdt_simd_lanes.restype = ctypes.c_int
    assert int(lib.crdt_simd_lanes()) in (4, 8, 16)


# ---- lane-parallel Poly1305 (the batched verify pass) ----------------------
# The batch engine's tag phase runs one FILE per vector lane (IFMA
# radix-2^44 where the CPU has it, portable radix-2^26 lanes otherwise).
# Beyond the decrypt-surface tests above — which exercise it end to end —
# these pin the MAC in isolation against the scalar core AND the
# pure-Python oracle, over exactly the ragged shapes the lockstep+mask
# machinery has to get right.


def _aead_mac_input(data: bytes) -> bytes:
    """The AEAD construction's Poly input for a zero-AAD message:
    data zero-padded to 16 bytes ‖ aad_len(0) ‖ ct_len."""
    pad = data + bytes(-len(data) % 16)
    return pad + (0).to_bytes(8, "little") + len(data).to_bytes(8, "little")


def _lane_tags(otks: list, msgs: list) -> list:
    import numpy as np

    lib = native.load()
    n = len(msgs)
    offsets = np.zeros(n + 1, np.uint64)
    for i, m in enumerate(msgs):
        offsets[i + 1] = offsets[i] + len(m)
    kp, _1 = native.in_ptr(b"".join(otks))
    mp, _2 = native.in_ptr(b"".join(msgs))
    tp, tags = native.out_buf(n * 16)
    lib.poly1305_aead_tags(
        kp, mp, offsets.ctypes.data_as(native.u64p), n, tp
    )
    return [tags[i * 16 : (i + 1) * 16].tobytes() for i in range(n)]


def test_poly1305_lane_batch_matches_scalar_and_oracle():
    """Ragged batches across every lane-fill class (1..17 files) and
    lengths hitting the lockstep/tail boundary cases: empty, sub-block,
    exact multiples of 16 (no pad block), and straddles — each lane's
    tag must equal the scalar core's AND the pure-Python oracle's."""
    import random

    lib = native.load()
    rng = random.Random(99)
    lens_pool = [0, 1, 15, 16, 17, 31, 32, 33, 100, 160, 161, 600, 1024]
    for n in (1, 2, 3, 5, 7, 8, 9, 15, 16, 17):
        otks = [secrets.token_bytes(32) for _ in range(n)]
        msgs = [
            secrets.token_bytes(rng.choice(lens_pool)) for _ in range(n)
        ]
        got = _lane_tags(otks, msgs)
        for i in range(n):
            mac_in = _aead_mac_input(msgs[i])
            assert got[i] == _poly1305_py(otks[i], mac_in), (n, i)
            kp, _1 = native.in_ptr(otks[i])
            mp, _2 = native.in_ptr(mac_in)
            tp, tag = native.out_buf(16)
            lib.poly1305_mac(kp, mp, len(mac_in), tp)
            assert got[i] == tag.tobytes(), (n, i)


def test_poly1305_lane_batch_equal_lengths_lockstep():
    """The pure lockstep fast region (all files the same length — the
    serving batch's common case): byte-exact vs the oracle, including
    the 16-multiple shape with no pad block at all."""
    for ln in (48, 64, 600):
        n = 16
        otks = [secrets.token_bytes(32) for _ in range(n)]
        msgs = [secrets.token_bytes(ln) for _ in range(n)]
        got = _lane_tags(otks, msgs)
        for i in range(n):
            assert got[i] == _poly1305_py(otks[i], _aead_mac_input(msgs[i]))


def test_poly1305_lane_extreme_length_skew():
    """One long file among tiny ones: the long lane keeps folding alone
    while every other lane sits drained under the carry-through mask."""
    otks = [secrets.token_bytes(32) for _ in range(8)]
    msgs = [secrets.token_bytes(4096)] + [
        secrets.token_bytes(i) for i in range(7)
    ]
    got = _lane_tags(otks, msgs)
    for i in range(8):
        assert got[i] == _poly1305_py(otks[i], _aead_mac_input(msgs[i])), i

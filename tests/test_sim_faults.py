"""Fault-tolerance regressions the simulator's fault layer targets:

* ingest quarantine — a torn/unauthenticated synced file (op or state)
  is skipped with the ``ingest_quarantined`` counter bumped and the
  cursor HELD, never an aborted read and never a cursor advanced past
  damage, so a repaired sync retries it (ISSUE-9 satellite 1);
* fs concurrent-GC tolerance — files and whole actor dirs disappearing
  between list and load (a second Core's compaction) skip-and-resample
  instead of raising mid-ingest (ISSUE-9 satellite 2);
* the FaultyStorage wrapper itself — deterministic decisions, density
  preserved under censoring, clean passthrough after heal().
"""

import asyncio
import os

import pytest

from crdt_enc_tpu.backends import (
    FsStorage,
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PlainKeyCryptor,
)
from crdt_enc_tpu.core import Core, OpenOptions, orset_adapter
from crdt_enc_tpu.models import canonical_bytes
from crdt_enc_tpu.utils import trace
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


def run(coro):
    return asyncio.run(coro)


def make_opts(storage, *, create=True, accel=None, cryptor=None):
    extra = {"accelerator": accel} if accel is not None else {}
    return OpenOptions(
        storage=storage,
        cryptor=cryptor if cryptor is not None else IdentityCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=orset_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=create,
        **extra,
    )


def _quarantined() -> int:
    return int(trace.snapshot()["counters"].get("ingest_quarantined", 0))


# ------------------------------------------------------ op quarantine
@pytest.mark.parametrize("backend", ["memory", "fs"])
def test_torn_op_blob_quarantined_then_retried(tmp_path, backend):
    """A truncated op blob must not abort read_remote: the good prefix
    folds, the damaged file quarantines (counter + held cursor), and a
    repaired sync delivers the tail."""

    async def go():
        if backend == "memory":
            remote = MemoryRemote()
            sa, sb = MemoryStorage(remote), MemoryStorage(remote)
        else:
            sa = FsStorage(str(tmp_path / "a"), str(tmp_path / "remote"))
            sb = FsStorage(str(tmp_path / "b"), str(tmp_path / "remote"))
        a = await Core.open(make_opts(sa))
        for i in range(3):
            await a.update(lambda s, i=i: s.add_ctx(a.actor_id, f"m{i}"))
        actor = a.actor_id

        # tear v2 mid-transfer
        if backend == "memory":
            intact = remote.ops[actor][2]
            remote.ops[actor][2] = intact[:5]
        else:
            path = os.path.join(
                str(tmp_path / "remote"), "ops", actor.hex(), "2"
            )
            intact = open(path, "rb").read()
            with open(path, "wb") as f:
                f.write(intact[:5])

        b = await Core.open(make_opts(sb))
        q0 = _quarantined()
        await b.read_remote()  # must NOT raise
        assert _quarantined() > q0
        # v1 folded, cursor held at the hole
        assert b.info().next_op_versions.get(actor) == 1
        assert b.with_state(lambda s: s.contains("m0"))
        assert not b.with_state(lambda s: s.contains("m1"))

        # the sync repairs the file: the retry ingests v2 and v3
        if backend == "memory":
            remote.ops[actor][2] = intact
        else:
            with open(path, "wb") as f:
                f.write(intact)
        await b.read_remote()
        assert b.info().next_op_versions.get(actor) == 3
        assert b.with_state(canonical_bytes) == a.with_state(canonical_bytes)

    run(go())


def test_torn_op_blob_quarantined_pipelined(tmp_path):
    """The same discipline through the accelerated pipelined bulk
    ingest (producer-side unwrap quarantine + chunk validation)."""
    from crdt_enc_tpu.parallel import TpuAccelerator

    async def go():
        sa = FsStorage(str(tmp_path / "a"), str(tmp_path / "remote"))
        sb = FsStorage(str(tmp_path / "b"), str(tmp_path / "remote"))
        a = await Core.open(make_opts(sa))
        for i in range(20):
            await a.update(lambda s, i=i: s.add_ctx(a.actor_id, f"m{i}"))
        actor = a.actor_id
        path = os.path.join(
            str(tmp_path / "remote"), "ops", actor.hex(), "10"
        )
        intact = open(path, "rb").read()
        with open(path, "wb") as f:
            f.write(intact[: len(intact) // 3])

        b = await Core.open(
            make_opts(sb, accel=TpuAccelerator(min_device_batch=1))
        )
        q0 = _quarantined()
        await b.read_remote()
        assert _quarantined() > q0
        assert b.info().next_op_versions.get(actor) == 9
        with open(path, "wb") as f:
            f.write(intact)
        await b.read_remote()
        assert b.info().next_op_versions.get(actor) == 20
        assert b.with_state(canonical_bytes) == a.with_state(canonical_bytes)

    run(go())


def test_torn_snapshot_quarantined_then_retried():
    """A truncated state snapshot is skipped (NOT added to read_states)
    and merged once the sync repairs it."""

    async def go():
        remote = MemoryRemote()
        a = await Core.open(make_opts(MemoryStorage(remote)))
        await a.update(lambda s: s.add_ctx(a.actor_id, "x"))
        await a.compact()
        (name, intact), = list(remote.states.items())
        remote.states[name] = intact[:7]

        b = await Core.open(make_opts(MemoryStorage(remote)))
        q0 = _quarantined()
        await b.read_remote()
        assert _quarantined() > q0
        assert not b.with_state(lambda s: s.contains("x"))
        assert name not in b.info().read_states

        remote.states[name] = intact
        await b.read_remote()
        assert b.with_state(canonical_bytes) == a.with_state(canonical_bytes)

    run(go())


def test_tampered_aead_op_quarantined():
    """With a real AEAD, a bit-flipped ciphertext fails authentication:
    quarantined per file, never folded, never a cursor advance."""
    pytest.importorskip("crdt_enc_tpu.backends.xchacha")
    from crdt_enc_tpu import native
    from crdt_enc_tpu.backends.xchacha import XChaChaCryptor

    try:
        native.load()
    except Exception:
        pytest.skip("native AEAD unavailable on this box")

    async def go():
        remote = MemoryRemote()
        a = await Core.open(
            make_opts(MemoryStorage(remote), cryptor=XChaChaCryptor())
        )
        await a.update(lambda s: s.add_ctx(a.actor_id, "good"))
        await a.update(lambda s: s.add_ctx(a.actor_id, "alsogood"))
        actor = a.actor_id
        blob = bytearray(remote.ops[actor][1])
        blob[-1] ^= 1  # break the tag
        remote.ops[actor][1] = bytes(blob)

        b = await Core.open(
            make_opts(MemoryStorage(remote), cryptor=XChaChaCryptor())
        )
        q0 = _quarantined()
        await b.read_remote()
        assert _quarantined() > q0
        # v1 damaged: nothing folds (v2 is past the hole), cursor at 0
        assert b.info().next_op_versions.get(actor) == 0
        assert not b.with_state(lambda s: s.contains("good"))

    run(go())


def test_unknown_key_still_loud():
    """Quarantine must NOT swallow MissingKeyError: ops sealed with a
    key whose metadata has not synced abort the read loudly (the
    pre-existing contract, re-pinned next to the quarantine paths)."""
    from crdt_enc_tpu.core import MissingKeyError

    async def go():
        ra, rb = MemoryRemote(), MemoryRemote()
        ca = await Core.open(make_opts(MemoryStorage(ra)))
        cb = await Core.open(make_opts(MemoryStorage(rb)))
        await cb.update(lambda s: s.add_ctx(cb.actor_id, "m"))
        for actor, log in rb.ops.items():
            ra.ops.setdefault(actor, {}).update(log)
        with pytest.raises(MissingKeyError):
            await ca.read_remote()

    run(go())


def test_service_quarantines_torn_tenant_file():
    """A torn op file reaching the FoldService front end quarantines
    instead of erroring the tenant cycle after cycle (the
    torn_op_service_abort fixture's bug class, unit-pinned)."""
    from crdt_enc_tpu.serve import FoldService, ServeConfig

    async def go():
        remote = MemoryRemote()
        a = await Core.open(make_opts(MemoryStorage(remote)))
        await a.update(lambda s: s.add_ctx(a.actor_id, "k"))
        await a.update(lambda s: s.add_ctx(a.actor_id, "k2"))
        actor = a.actor_id
        intact = remote.ops[actor][1]
        remote.ops[actor][1] = intact[:4]

        b = await Core.open(make_opts(MemoryStorage(remote)))
        service = FoldService([b], ServeConfig())
        q0 = _quarantined()
        (res,) = await service.run_cycle()
        assert res.error is None, res.error
        assert _quarantined() > q0
        assert b.info().next_op_versions.get(actor) == 0  # cursor held

        remote.ops[actor][1] = intact
        (res,) = await service.run_cycle()
        assert res.error is None
        assert b.info().next_op_versions.get(actor) == 2
        assert b.with_state(canonical_bytes) == a.with_state(canonical_bytes)

    run(go())


# ------------------------------------------------ writer dot-reuse guard
def test_reopened_producer_relearns_own_history():
    """The dot_reuse_crash_reopen fixture's bug class, unit-pinned: a
    producer that crashes and writes again after a cold reopen must
    NOT mint Orswot dots from its stale clock (they'd collide with its
    pre-crash events and break convergence for every replica) — the
    first write auto-ingests its own durable history instead."""

    async def go():
        remote = MemoryRemote()
        storage = MemoryStorage(remote)
        a = await Core.open(make_opts(storage))
        await a.update(lambda s: s.add_ctx(a.actor_id, "pre-crash"))
        # crash: the Core object is dropped, storage survives
        b = await Core.open(make_opts(storage, create=False))
        await b.update(lambda s: s.add_ctx(b.actor_id, "post-reopen"))
        # the write re-learned v1 first: both adds live, distinct dots
        assert b.with_state(lambda s: s.contains("pre-crash"))
        assert b.with_state(lambda s: s.contains("post-reopen"))
        reader = await Core.open(make_opts(MemoryStorage(remote)))
        await reader.read_remote()
        assert reader.with_state(canonical_bytes) == b.with_state(
            canonical_bytes
        )

    run(go())


def test_reopened_producer_refuses_write_when_history_hidden():
    """When the remote does not (yet) show the producer's own recorded
    history — partial sync after a crash — the write is refused loudly
    (StaleWriterError) instead of silently reusing event ids."""
    from crdt_enc_tpu.core import StaleWriterError

    class BlindStorage(MemoryStorage):
        """A remote where this replica's own files have not synced
        back: nothing is listed, nothing loads."""

        async def list_op_actors(self):
            return []

        async def list_state_names(self):
            return []

        async def load_ops(self, wanted):
            return []

        async def stat_ops(self, wanted):
            return []

    async def go():
        remote = MemoryRemote()
        storage = MemoryStorage(remote)
        a = await Core.open(make_opts(storage))
        await a.update(lambda s: s.add_ctx(a.actor_id, "pre-crash"))
        blind = BlindStorage(remote)
        blind._local_meta = storage._local_meta  # same replica identity
        b = await Core.open(make_opts(blind, create=False))
        with pytest.raises(StaleWriterError):
            await b.update(lambda s: s.add_ctx(b.actor_id, "unsafe"))

    run(go())


def test_reopen_refuses_key_remint_when_meta_hidden():
    """The key_dot_reuse_partial_meta fixture's bug class, unit-pinned:
    a replica reopening while its own key-register write is hidden (a
    partially synced meta listing) must NOT re-bootstrap a data key —
    the fresh mint would reuse keys-ORSet dot (actor, 1), the Orswot
    merge would kill one key's material, and the latest-register
    tie-break can leave the whole remote pointing at a dead id
    (DanglingLatestKey on every open).  The durable
    ``LocalMeta.last_key_dot`` cursor refuses the mint loudly instead;
    once the register syncs back, the reopen needs no mint at all and
    the fleet's key material survives intact."""
    from crdt_enc_tpu.core import MissingKeyError

    class MetaBlindStorage(MemoryStorage):
        """The converged key register has not synced back."""

        async def list_remote_meta_names(self):
            return []

    async def go():
        remote = MemoryRemote()
        storage = MemoryStorage(remote)
        a = await Core.open(make_opts(storage))
        await a.update(lambda s: s.add_ctx(a.actor_id, "sealed-pre-crash"))
        key_id = a._data.keys.latest_key().id
        # crash; reopen sees NO meta files → bootstrap wants to mint,
        # but dot (actor, 1) was already spent on the pre-crash key
        blind = MetaBlindStorage(remote)
        blind._local_meta = storage._local_meta
        with pytest.raises(MissingKeyError):
            await Core.open(make_opts(blind, create=False))
        # after the sync heals, the reopen needs no mint: same key,
        # no dangling register, data still readable
        b = await Core.open(make_opts(storage, create=False))
        assert b._data.keys.latest_key().id == key_id
        await b.read_remote()
        assert b.with_state(lambda s: s.contains("sealed-pre-crash"))
        reader = await Core.open(make_opts(MemoryStorage(remote)))
        await reader.read_remote()
        assert reader.with_state(canonical_bytes) == b.with_state(
            canonical_bytes
        )

    run(go())


# -------------------------------------------------- fs concurrent-GC races
def test_fs_reader_survives_real_concurrent_gc(tmp_path):
    """The satellite-2 race, deterministically interleaved: B lists the
    remote, then a REAL second Core's compaction GCs those exact files
    before B loads them.  B's ingest must skip-and-resample (missing =
    already-covered), never raise, and converge on the next read."""

    class RacingStorage(FsStorage):
        """Runs a callback between list and load — the adversarial
        interleaving made deterministic."""

        race = None

        async def load_states(self, names):
            if RacingStorage.race is not None:
                cb, RacingStorage.race = RacingStorage.race, None
                await cb()
            return await super().load_states(names)

        async def load_ops(self, wanted):
            if RacingStorage.race is not None:
                cb, RacingStorage.race = RacingStorage.race, None
                await cb()
            return await super().load_ops(wanted)

    async def go():
        remote = str(tmp_path / "remote")
        a = await Core.open(
            make_opts(FsStorage(str(tmp_path / "a"), remote))
        )
        for i in range(3):
            await a.update(lambda s, i=i: s.add_ctx(a.actor_id, f"m{i}"))
        await a.compact()  # snapshot v1..v3 + op GC
        await a.update(lambda s: s.add_ctx(a.actor_id, "tail"))

        b = await Core.open(
            make_opts(RacingStorage(str(tmp_path / "b"), remote))
        )

        async def gc():
            # A compacts again: removes the snapshot B just listed and
            # the op tail B is about to load
            await a.compact()

        RacingStorage.race = gc
        await b.read_remote()  # must not raise
        await b.read_remote()  # resample: the new snapshot covers it all
        assert b.with_state(canonical_bytes) == a.with_state(canonical_bytes)

    run(go())


def test_fs_publish_survives_actor_dir_rmdir(tmp_path, monkeypatch):
    """remove_ops rmdir's an emptied actor dir; a concurrent publisher
    whose dir vanishes between makedirs and the tmp open must retry,
    not surface FileNotFoundError (satellite 2, write side)."""
    import shutil

    from crdt_enc_tpu.backends import fs as fs_mod

    d = str(tmp_path / "ops" / "aa")
    target = os.path.join(d, "1")
    real_write_tmp = fs_mod._write_tmp
    calls = {"n": 0}

    def racing_write_tmp(dd, data):
        calls["n"] += 1
        if calls["n"] == 1:
            # the GC wins the race after our makedirs
            shutil.rmtree(dd)
            raise FileNotFoundError(dd)
        return real_write_tmp(dd, data)

    monkeypatch.setattr(fs_mod, "_write_tmp", racing_write_tmp)
    fs_mod._write_file_new(target, b"payload")
    assert open(target, "rb").read() == b"payload"
    assert calls["n"] == 2


def test_fs_publish_survives_vanishing_collider(tmp_path, monkeypatch):
    """os.link says EEXIST but the collider is GC'd before the
    idempotence check reads it: retry the link instead of raising
    FileNotFoundError out of a content-addressed store."""
    from crdt_enc_tpu.backends import fs as fs_mod

    d = str(tmp_path / "states")
    target = os.path.join(d, "HASH")
    real_link = os.link
    calls = {"n": 0}

    def racing_link(src, dst, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            # a concurrent writer's file existed at link time but was
            # collected before our equality check could open it
            raise FileExistsError(dst)
        return real_link(src, dst, **kw)

    monkeypatch.setattr(fs_mod.os, "link", racing_link)
    fs_mod._write_file_new(target, b"blob")
    assert open(target, "rb").read() == b"blob"
    assert calls["n"] == 2


def test_fs_op_publish_burns_vanished_collider_version(tmp_path, monkeypatch):
    """Version-addressed op files must NOT relink after a vanished
    collider: the collider existed (a peer may have folded it into a
    snapshot), so republishing different content at that version would
    be invisible to every cursor already past it.  The burned version
    surfaces as FileExistsError and the producer probes forward."""
    from crdt_enc_tpu.backends import fs as fs_mod

    d = str(tmp_path / "ops" / "aa")
    target = os.path.join(d, "1")
    real_link = os.link
    calls = {"n": 0}

    def racing_link(src, dst, **kw):
        calls["n"] += 1
        if calls["n"] == 1:
            raise FileExistsError(dst)  # collider present at link time...
        return real_link(src, dst, **kw)  # ...but GC'd before the check

    monkeypatch.setattr(fs_mod.os, "link", racing_link)
    with pytest.raises(FileExistsError):
        fs_mod._write_file_new(
            target, b"new-content", relink_vanished_collider=False
        )
    assert not os.path.exists(target)  # nothing republished at v1


def test_systemic_decrypt_failure_escalates_not_quarantines():
    """Every file of a multi-file batch failing to decrypt is a dead
    cryptor / damaged key register, not per-file damage: read_remote
    must raise IngestDecryptError loudly instead of quarantining the
    whole backlog into a silently-stuck replica."""
    from crdt_enc_tpu.core import IngestDecryptError

    async def go():
        remote = MemoryRemote()
        a = await Core.open(make_opts(MemoryStorage(remote)))
        await a.update(lambda s: s.add_ctx(a.actor_id, "x"))
        await a.update(lambda s: s.add_ctx(a.actor_id, "y"))

        class DeadCryptor(IdentityCryptor):
            async def decrypt(self, key, data):
                raise RuntimeError("cryptor backend is broken")

        b = await Core.open(
            make_opts(MemoryStorage(remote), cryptor=DeadCryptor())
        )
        with pytest.raises(IngestDecryptError) as ei:
            await b.read_remote()
        assert "backend is broken" in repr(ei.value.__cause__)
        # nothing advanced: the backlog is intact for after the repair
        assert b.info().next_op_versions.get(a.actor_id) == 0

    run(go())


def test_own_tail_probe_failure_retries_next_write():
    """The dot-reuse guard must not fail open permanently: a transient
    stat_ops error on the first write leaves the incarnation's
    own-history check unsatisfied, so the next write probes again."""

    class FlakyStatStorage(MemoryStorage):
        stat_calls = 0
        fail_next = False

        async def stat_ops(self, wanted):
            type(self).stat_calls += 1
            if type(self).fail_next:
                type(self).fail_next = False
                raise OSError("transient storage error")
            return await super().stat_ops(wanted)

    async def go():
        remote = MemoryRemote()
        storage = FlakyStatStorage(remote)
        FlakyStatStorage.stat_calls = 0
        c = await Core.open(make_opts(storage))
        base = FlakyStatStorage.stat_calls  # open() samples replication
        FlakyStatStorage.fail_next = True
        await c.update(lambda s: s.add_ctx(c.actor_id, "m1"))  # probe fails
        first = FlakyStatStorage.stat_calls
        await c.update(lambda s: s.add_ctx(c.actor_id, "m2"))  # re-probes
        second = FlakyStatStorage.stat_calls
        assert first > base and second > first
        await c.update(lambda s: s.add_ctx(c.actor_id, "m3"))  # now cached
        assert FlakyStatStorage.stat_calls == second

    run(go())


# ------------------------------------------------- FaultyStorage itself
def test_faulty_storage_deterministic_and_heals():
    from crdt_enc_tpu.sim import FaultConfig, FaultyStorage

    async def go():
        remote = MemoryRemote()
        writer = MemoryStorage(remote)
        actor = b"\x01" * 16
        for v in range(1, 9):
            await writer.store_ops(actor, v, f"payload-{v}".encode() * 4)

        def wrap():
            return FaultyStorage(
                MemoryStorage(remote),
                FaultConfig(torn_read=0.5, partial_list=0.3),
                seed=7, name="r0",
            )

        async def observe(w):
            out = []
            for _ in range(4):
                out.append(await w.load_ops([(actor, 1)]))
                out.append(await w.list_op_actors())
            return out

        a = await observe(wrap())
        b = await observe(wrap())
        assert a == b  # pure function of (seed, call sequence)
        w = wrap()
        assert w.stats.total() == 0 or True
        w.heal()
        clean = await w.load_ops([(actor, 1)])
        assert clean == await writer.load_ops([(actor, 1)])

    run(go())


def test_faulty_storage_censor_preserves_density():
    """Delayed visibility may hide op files, but whatever is delivered
    stays a gap-free per-actor prefix — the storage contract the core's
    dense scan depends on — and ticks eventually reveal everything."""
    from crdt_enc_tpu.sim import FaultConfig, FaultyStorage

    async def go():
        remote = MemoryRemote()
        writer = MemoryStorage(remote)
        actors = [b"\x01" * 16, b"\x02" * 16]
        for actor in actors:
            for v in range(1, 6):
                await writer.store_ops(actor, v, b"x" * 8)
        w = FaultyStorage(
            MemoryStorage(remote),
            FaultConfig(delay_visibility=0.9, delay_max_ticks=2),
            seed=3, name="r1",
        )
        for round_ in range(6):
            files = await w.load_ops([(a, 1) for a in actors])
            per_actor: dict = {}
            for actor, version, _ in files:
                per_actor.setdefault(actor, []).append(version)
            for actor, versions in per_actor.items():
                assert versions == list(range(1, len(versions) + 1)), (
                    round_, versions,
                )
            w.tick()
        # all reveal delays expired by now
        files = await w.load_ops([(a, 1) for a in actors])
        assert len(files) == 10

    run(go())


def test_faulty_storage_write_crash_before_or_after():
    """SimCrash fires on write steps; crash-AFTER leaves the write
    durable, crash-BEFORE leaves nothing — both must occur across a
    seed sweep (the adversary genuinely explores both worlds).  A
    landed crash-AFTER write must still register as the replica's OWN
    (immediately visible even under max visibility delay): the wrapper
    models a crashed process, not a replica blind to its own durable
    files."""
    from crdt_enc_tpu.sim import FaultConfig, FaultyStorage, SimCrash

    async def go():
        before = after = 0
        actor = b"\x03" * 16
        for seed in range(40):
            remote = MemoryRemote()
            w = FaultyStorage(
                MemoryStorage(remote),
                FaultConfig(write_crash=1.0, delay_visibility=1.0),
                seed=seed, name="r0",
            )
            with pytest.raises(SimCrash):
                await w.store_ops(actor, 1, b"data")
            if remote.ops:
                after += 1
                w.cfg = FaultConfig(delay_visibility=1.0)  # crashes off
                files = await w.load_ops([(actor, 1)])
                assert [v for _, v, _ in files] == [1], "own write hidden"
            else:
                before += 1
        assert before > 0 and after > 0

    run(go())

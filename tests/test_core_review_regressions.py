"""Regressions for review findings: key-id envelope selection, durable
producer cursor, and race-free immutable op publishes."""

import asyncio
import hashlib

import pytest

from crdt_enc_tpu.backends import (
    FsStorage,
    IdentityCryptor,
    MemoryRemote,
    MemoryStorage,
    PlainKeyCryptor,
)
from crdt_enc_tpu.core import Core, Cryptor, OpenOptions, gcounter_adapter
from crdt_enc_tpu.utils import VersionBytes
from crdt_enc_tpu.utils.versions import (
    DEFAULT_DATA_VERSION_1,
    IDENTITY_DATA_VERSION_1,
    IDENTITY_KEY_VERSION_1,
)


class CheckedCryptor(IdentityCryptor):
    """Identity transport that *verifies the key*: wrong key ⇒ hard error,
    like a real AEAD tag failure."""

    async def encrypt(self, key: VersionBytes, data: bytes) -> bytes:
        key.ensure_version(IDENTITY_KEY_VERSION_1)
        tag = hashlib.sha3_256(key.content + data).digest()[:8]
        return VersionBytes(IDENTITY_DATA_VERSION_1, tag + data).serialize()

    async def decrypt(self, key: VersionBytes, data: bytes) -> bytes:
        key.ensure_version(IDENTITY_KEY_VERSION_1)
        body = (
            VersionBytes.deserialize(data)
            .ensure_version(IDENTITY_DATA_VERSION_1)
            .content
        )
        tag, payload = body[:8], body[8:]
        if hashlib.sha3_256(key.content + payload).digest()[:8] != tag:
            raise ValueError("wrong key (simulated AEAD tag mismatch)")
        return payload


def make_opts(storage, cryptor=None, create=True, **kw):
    return OpenOptions(
        storage=storage,
        cryptor=cryptor or CheckedCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=gcounter_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=create,
        **kw,
    )


def test_concurrent_bootstrap_two_keys_both_decryptable():
    """Two replicas bootstrap disjoint keys before their remotes sync (the
    syncthing split-brain); after sync each must decrypt the other's files
    via the key id recorded in the envelope."""

    async def go():
        ra, rb = MemoryRemote(), MemoryRemote()
        ca = await Core.open(make_opts(MemoryStorage(ra)))
        cb = await Core.open(make_opts(MemoryStorage(rb)))
        await ca.update(lambda s: s.inc(ca.actor_id, 3))
        await cb.update(lambda s: s.inc(cb.actor_id, 4))
        # the sync tool merges the trees (union of immutable files)
        ra.metas.update(rb.metas)
        ra.states.update(rb.states)
        for actor, log in rb.ops.items():
            ra.ops.setdefault(actor, {}).update(log)
        await ca.read_remote()
        assert ca.with_state(lambda s: s.read()) == 7

    asyncio.run(go())


def test_unknown_key_is_loud_not_silent():
    async def go():
        ra, rb = MemoryRemote(), MemoryRemote()
        ca = await Core.open(make_opts(MemoryStorage(ra)))
        cb = await Core.open(make_opts(MemoryStorage(rb)))
        await cb.update(lambda s: s.inc(cb.actor_id, 4))
        # ops sync over but the key metadata does NOT (partial sync)
        for actor, log in rb.ops.items():
            ra.ops.setdefault(actor, {}).update(log)
        from crdt_enc_tpu.core import MissingKeyError

        with pytest.raises(MissingKeyError):
            await ca.read_remote()

    asyncio.run(go())


def test_producer_cursor_survives_restart(tmp_path):
    """Write, compact, 'restart' the process, write again WITHOUT an
    explicit read_remote: the new op file must land past the compacted
    range so consumers whose scan cursor is already beyond v1 still
    find it.  (Without the durable cursor it lands at v1 and is
    invisible to them forever — the silent-loss scenario.)

    Checkpointing is disabled for the restart, pinning the cold-open
    path.  Since the dot-reuse fix (``Core._ensure_own_history``,
    simulator-discovered: tests/data/sim/dot_reuse_crash_reopen.json),
    the first write of a reopened producer auto-ingests its own durable
    history first — deriving against an empty clock would re-mint
    pre-crash event ids — so the increment CONTINUES from the resumed
    state (15, not an absolute 10), identical to the warm-open twin
    below."""

    async def go():
        local, remote = str(tmp_path / "l1"), str(tmp_path / "r")
        c1 = await Core.open(make_opts(FsStorage(local, remote)))
        actor = c1.actor_id
        await c1.update(lambda s: s.inc(actor, 3))
        await c1.update(lambda s: s.inc(actor, 2))
        await c1.compact()
        # a consumer ingests the snapshot: its scan cursor is now v2
        c2 = await Core.open(make_opts(FsStorage(str(tmp_path / "l2"), remote)))
        await c2.read_remote()
        assert c2.with_state(lambda s: s.read()) == 5
        # restart the producer COLD; write immediately (no read_remote)
        c1b = await Core.open(
            make_opts(FsStorage(local, remote), create=False, checkpoint=False)
        )
        assert c1b.actor_id == actor
        await c1b.update(lambda s: s.inc(actor, 10))
        # the write re-learned its own history (snapshot = 5) first
        assert c1b.with_state(lambda s: s.read()) == 15
        # the op file must be at v3 — past the compacted v1..v2 range
        ops_dir = tmp_path / "r" / "ops" / actor.hex()
        assert sorted(p.name for p in ops_dir.iterdir()) == ["3"]
        await c2.read_remote()
        assert c2.with_state(lambda s: s.read()) == 15

    asyncio.run(go())


def test_producer_restart_warm_checkpoint_continues_increments(tmp_path):
    """The checkpointed restart (default): the warm open restores the
    compacted state, so an immediate write continues from it — the
    resume protocol's result without an explicit read_remote."""

    async def go():
        local, remote = str(tmp_path / "l1"), str(tmp_path / "r")
        c1 = await Core.open(make_opts(FsStorage(local, remote)))
        actor = c1.actor_id
        await c1.update(lambda s: s.inc(actor, 3))
        await c1.update(lambda s: s.inc(actor, 2))
        await c1.compact()
        c1b = await Core.open(make_opts(FsStorage(local, remote), create=False))
        assert c1b.opened_from_checkpoint
        await c1b.update(lambda s: s.inc(actor, 10))
        ops_dir = tmp_path / "r" / "ops" / actor.hex()
        assert sorted(p.name for p in ops_dir.iterdir()) == ["3"]
        c2 = await Core.open(make_opts(FsStorage(str(tmp_path / "l2"), remote)))
        await c2.read_remote()
        assert c2.with_state(lambda s: s.read()) == 15

    asyncio.run(go())


def test_restart_with_resume_protocol_increments_correctly(tmp_path):
    """The documented resume: open + read_remote, then write — increments
    continue from the folded state."""

    async def go():
        local, remote = str(tmp_path / "l1"), str(tmp_path / "r")
        c1 = await Core.open(make_opts(FsStorage(local, remote)))
        await c1.update(lambda s: s.inc(c1.actor_id, 5))
        await c1.compact()
        c1b = await Core.open(make_opts(FsStorage(local, remote), create=False))
        await c1b.read_remote()
        await c1b.update(lambda s: s.inc(c1b.actor_id, 10))
        c2 = await Core.open(make_opts(FsStorage(str(tmp_path / "l2"), remote)))
        await c2.read_remote()
        assert c2.with_state(lambda s: s.read()) == 15

    asyncio.run(go())


def test_store_ops_collision_is_detected(tmp_path):
    async def go():
        remote = str(tmp_path / "r")
        s1 = FsStorage(str(tmp_path / "l1"), remote)
        s2 = FsStorage(str(tmp_path / "l2"), remote)
        actor = b"\x01" * 16
        await s1.store_ops(actor, 1, b"first writer wins")
        with pytest.raises(FileExistsError):
            await s2.store_ops(actor, 1, b"second writer must fail")
        # identical content is an idempotent replay, not an error
        await s2.store_ops(actor, 1, b"first writer wins")
        [(a, v, data)] = await s1.load_ops([(actor, 1)])
        assert data == b"first writer wins"

    asyncio.run(go())

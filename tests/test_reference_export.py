"""Reference-remote exporter: the byte-level inverse of the importer.

Round-trip validation: a replica of this framework exports to the
reference layout, and the export must (a) parse with the importer's
blob opener layer by layer, and (b) re-import into a fresh replica of
this framework with a canonically identical state — so any drift from
the reference's wire format (as pinned by the importer's in-tree
citations) breaks these tests.
"""

import asyncio
import os
import secrets
import uuid as uuidm

import pytest

from crdt_enc_tpu.backends import FsStorage, PlainKeyCryptor, XChaChaCryptor
from crdt_enc_tpu.core import Core, OpenOptions, mvreg_adapter
from crdt_enc_tpu.models import canonical_bytes
from crdt_enc_tpu.tools.export_reference import (
    ExportStats,
    export_reference_log,
    export_reference_state,
    mvreg_op_untranslator,
    mvreg_state_untranslator,
    seal_reference_blob,
)
from crdt_enc_tpu.tools.import_reference import (
    ReferenceFormatError,
    import_reference_remote,
    mvreg_translator,
    open_reference_blob,
)
from crdt_enc_tpu.utils import codec
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

APP_DATA_VERSION = uuidm.UUID("11111111-2222-3333-4444-555555555555").bytes


def run(coro):
    return asyncio.run(coro)


def opts(tmp_path, name, create=True):
    return OpenOptions(
        storage=FsStorage(
            str(tmp_path / name / "local"), str(tmp_path / name / "remote")
        ),
        cryptor=XChaChaCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=mvreg_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=create,
    )


def shared_opts(tmp_path, local_name, remote_name):
    o = opts(tmp_path, local_name)
    o.storage = FsStorage(
        str(tmp_path / local_name), str(tmp_path / remote_name / "remote")
    )
    return o


# ---- blob level -------------------------------------------------------------


def test_seal_reference_blob_is_openable_by_the_importer():
    key = secrets.token_bytes(32)
    payload = codec.pack([{"clock": {"dots": {b"\x00" * 16: 1}}, "val": 9}])
    blob = seal_reference_blob(key, payload, APP_DATA_VERSION)
    ver, out = open_reference_blob(key, blob)
    assert ver == APP_DATA_VERSION
    assert bytes(out) == payload
    # wrong key must fail the AEAD, not parse garbage
    from crdt_enc_tpu.backends.xchacha import AeadError

    with pytest.raises(AeadError):
        open_reference_blob(secrets.token_bytes(32), blob)


def test_untranslators_invert_the_translator():
    from crdt_enc_tpu.models import MVReg
    from crdt_enc_tpu.models.vclock import VClock

    a, b = uuidm.UUID(int=1).bytes, uuidm.UUID(int=2).bytes
    reg = MVReg()
    reg.apply(reg.write_ctx(a, 41))
    reg.apply(reg.write_ctx(b, 42))

    # state untranslation → translator → ops that rebuild the same state
    ref_ops = mvreg_state_untranslator(reg)
    back = mvreg_translator(codec.pack(ref_ops))
    rebuilt = MVReg()
    for op in back:
        rebuilt.apply(op)
    assert canonical_bytes(rebuilt) == canonical_bytes(reg)

    # op untranslation round-trips value and clock
    op = reg.write_ctx(a, "x")
    (got,) = mvreg_translator(codec.pack([mvreg_op_untranslator(op)]))
    assert got.value == "x"
    assert got.clock.counters == op.clock.counters


# ---- end-to-end: export → import -------------------------------------------


def _populate(tmp_path):
    """Three writers on one shared remote with dominated + concurrent
    register writes."""

    async def go():
        a = await Core.open(shared_opts(tmp_path, "a", "shared"))
        b = await Core.open(shared_opts(tmp_path, "b", "shared"))
        c = await Core.open(shared_opts(tmp_path, "c", "shared"))
        await a.update(lambda s: s.write_ctx(a.actor_id, 1))
        await b.read_remote()
        await b.update(lambda s: s.write_ctx(b.actor_id, 2))  # dominates 1
        await c.update(lambda s: s.write_ctx(c.actor_id, 3))  # concurrent
        await a.read_remote()
        return a

    return run(go())


@pytest.mark.parametrize("mode", ["state", "log"])
def test_export_reimports_identically(tmp_path, mode):
    src = _populate(tmp_path)
    key = secrets.token_bytes(32)
    ref_remote = tmp_path / "ref-remote"

    async def go():
        if mode == "state":
            stats = await export_reference_state(
                src, ref_remote, key, APP_DATA_VERSION
            )
            assert stats.op_files == 1 and stats.actors == 1
            assert stats.ops == 2  # the two surviving concurrent values
        else:
            stats = await export_reference_log(
                src, ref_remote, key, APP_DATA_VERSION
            )
            assert stats.actors == 3 and stats.op_files == 3 and stats.ops == 3
            # reference layout: Display-named dirs, files from version 0
            d = ref_remote / "ops" / str(uuidm.UUID(bytes=src.actor_id))
            assert sorted(os.listdir(d)) == ["0"]

        dest = await Core.open(opts(tmp_path, "reimport"))
        await import_reference_remote(ref_remote, dest, key)
        await src.read_remote()
        assert sorted(dest.with_state(lambda s: s.read().values)) == [2, 3]
        assert dest.with_state(canonical_bytes) == src.with_state(
            canonical_bytes
        )

    run(go())


def test_log_export_refuses_compacted_source(tmp_path):
    src = _populate(tmp_path)
    key = secrets.token_bytes(32)

    async def go():
        await src.compact()
        with pytest.raises(ReferenceFormatError, match="state"):
            await export_reference_log(
                src, tmp_path / "ref-remote", key, APP_DATA_VERSION
            )
        # state mode still carries the full compacted history
        stats = await export_reference_state(
            src, tmp_path / "ref-remote", key, APP_DATA_VERSION
        )
        dest = await Core.open(opts(tmp_path, "reimport"))
        await import_reference_remote(tmp_path / "ref-remote", dest, key)
        assert sorted(dest.with_state(lambda s: s.read().values)) == [2, 3]

    run(go())


def test_export_cli(tmp_path, capsys):
    from crdt_enc_tpu.tools.export_reference import main

    src = _populate(tmp_path)
    key = secrets.token_bytes(32)
    rc = main([
        str(tmp_path / "a"), str(tmp_path / "shared" / "remote"),
        str(tmp_path / "ref-remote"),
        "--key-hex", key.hex(),
        "--data-version-uuid", str(uuidm.UUID(bytes=APP_DATA_VERSION)),
        "--mode", "log",
    ])
    assert rc == 0
    assert "exported 3 ops in 3 files" in capsys.readouterr().out

    async def check():
        dest = await Core.open(opts(tmp_path, "reimport"))
        await import_reference_remote(tmp_path / "ref-remote", dest, key)
        assert sorted(dest.with_state(lambda s: s.read().values)) == [2, 3]

    run(check())


def test_log_export_refuses_gapped_source(tmp_path):
    """A mid-log hole with files stranded beyond it must refuse the
    export (load_ops' dense scan would silently truncate), mirroring the
    importer's gap refusal."""
    import os as _os

    src = _populate(tmp_path)
    key = secrets.token_bytes(32)

    async def go():
        # punch a hole in one actor's log: keep v1, drop v2... need a log
        # with >1 file — write more ops from replica a first
        for i in range(3):
            await src.update(lambda s, i=i: s.write_ctx(src.actor_id, 10 + i))
        ops_dir = (
            tmp_path / "shared" / "remote" / "ops" / src.actor_id.hex()
        )
        versions = sorted(int(n) for n in _os.listdir(ops_dir))
        assert len(versions) >= 3
        _os.remove(ops_dir / str(versions[1]))
        with pytest.raises(ReferenceFormatError, match="stranded"):
            await export_reference_log(
                src, tmp_path / "ref-remote", key, APP_DATA_VERSION
            )

    run(go())

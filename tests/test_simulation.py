"""Adversarial whole-stack simulation: the sim/ subsystem drives real
Cores (host + TPU-accelerated + FoldService-sealed in one history) over
a shared remote behind fault-injecting storage wrappers, and checks the
full quiescence invariant set — cross-replica byte equality, fresh-host
oracle refold, warm≡cold reopen, replication monotonicity, and fsck
cleanliness (docs/simulation.md).

Tier-1 keeps the fast smokes (3 adversarial seeds, an fs-backend run, a
chunked-session stress, determinism, the committed-fixture replays);
the fleet-scale acceptance run (8 replicas × 500 steps, every fault
class) is marked ``slow``.
"""

import glob
import json
import os

import pytest

from crdt_enc_tpu.sim import (
    FaultConfig,
    Schedule,
    Step,
    Violation,
    generate,
    run_schedule,
    shrink,
)

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "data", "sim")


# ---------------------------------------------------------------- smokes
@pytest.mark.parametrize("seed", [0, 1, 2])
def test_adversarial_schedule_converges(seed):
    """The tier-1 smoke: every fault class enabled, memory backend,
    mixed host/TPU replicas — all five invariants at quiescence."""
    schedule = generate(seed, 4, 80, FaultConfig.all_faults())
    result = run_schedule(schedule)
    assert result.ok, result.violation
    assert result.checks_run >= 1
    # the adversary genuinely showed up
    assert sum(result.fault_stats.values()) > 0


def test_adversarial_schedule_converges_fs(tmp_path):
    """The same property over the production fs backend (concurrent
    compactors GC real files under real readers)."""
    schedule = generate(1, 3, 60, FaultConfig.all_faults(), backend="fs")
    result = run_schedule(schedule, tmpdir=str(tmp_path))
    assert result.ok, result.violation


def test_adversarial_schedule_converges_chunked_sessions(tmp_path, monkeypatch):
    """Ingest pipeline maximally stressed under faults: tiny fs chunks
    and instant session promotion force multi-chunk fold sessions on
    every accelerated sync (the PR-1/PR-3 machinery in the loop)."""
    import crdt_enc_tpu.parallel.session as S
    from crdt_enc_tpu.backends.fs import FsStorage

    monkeypatch.setattr(S, "BUFFER_BYTES", 64)
    monkeypatch.setattr(FsStorage, "CHUNK_BYTES", 2048)
    schedule = generate(7, 3, 50, FaultConfig.all_faults(), backend="fs")
    result = run_schedule(schedule, tmpdir=str(tmp_path))
    assert result.ok, result.violation


# --------------------------------------------------------- determinism
def test_deterministic_replay_from_seed():
    """One seed names one exact history: fault pattern, final states,
    and cursors replay bit-for-bit (the shrink/replay substrate)."""
    schedule = generate(5, 4, 70, FaultConfig.all_faults())
    r1 = run_schedule(schedule)
    r2 = run_schedule(schedule)
    assert r1.ok, r1.violation
    assert r1.fingerprint == r2.fingerprint
    assert r1.fault_stats == r2.fault_stats
    assert sum(r1.fault_stats.values()) > 0


# ------------------------------------------------- FoldService in the loop
def test_service_sealed_tenants_in_faulty_history():
    """Service-sealed compactions and solo compactors interleave over
    the same faulty remote and still converge byte-identically — the
    serving layer rides the sim gate like any other replication-surface
    change (ISSUE satellite)."""
    base = generate(6, 4, 50, FaultConfig.all_faults())
    # guarantee the service actually seals, interleaved with solo
    # compactors, whatever the seed's organic mix was
    steps = list(base.steps)
    steps += [
        Step("service", 0, 1),
        Step("add", 2, 3),
        Step("compact", 2),
        Step("service", 3, 3),
        Step("add", 1, 5),
        Step("service", 1, 2),
    ]
    schedule = base.with_steps(steps)
    result = run_schedule(schedule)
    assert result.ok, result.violation
    assert result.service_cycles >= 3


# ------------------------------------------------------------- fixtures
def _fixture_files():
    return sorted(glob.glob(os.path.join(FIXTURE_DIR, "*.json")))

def test_shrunk_fixtures_replay_clean(tmp_path):
    """Every committed shrunk failure is a permanent regression test:
    the schedules that once violated an invariant (see each fixture's
    "violation"/"note") must now pass the full check set."""
    files = _fixture_files()
    assert len(files) >= 2, "at least two shrunk fixtures must be committed"
    for path in files:
        with open(path) as f:
            obj = json.load(f)
        schedule = Schedule.from_obj(obj)
        # the fixture records what it USED to violate — or, for
        # behavioral fixtures (e.g. the delta fallback-to-snapshot
        # schedule), a note naming the path it pins
        assert obj.get("violation", {}).get("invariant") or obj["note"]
        result = run_schedule(
            schedule,
            tmpdir=str(tmp_path / os.path.basename(path).removesuffix(".json")),
        )
        assert result.ok, (path, result.violation)


def test_fixture_dir_fully_referenced():
    """Nothing rides silently in the fixture dir: every file is a
    .json the glob above (and the replay CLI in run_checks.sh)
    executes — an unreplayable stray would otherwise look committed
    and covered while testing nothing."""
    strays = [
        e for e in os.listdir(FIXTURE_DIR) if not e.endswith(".json")
    ]
    assert strays == []


def test_fixture_schema_roundtrip():
    schedule = generate(3, 3, 20, FaultConfig.all_faults())
    again = Schedule.from_obj(schedule.to_obj())
    assert again.to_obj() == schedule.to_obj()
    with pytest.raises(ValueError):
        Schedule.from_obj({**schedule.to_obj(), "v": 99})
    with pytest.raises(ValueError):
        bad = schedule.to_obj()
        bad["steps"] = [["add", 17, 0]]  # replica out of range
        Schedule.from_obj(bad)


# -------------------------------------------------------------- shrinker
def test_shrinker_minimizes_steps_and_faults():
    """ddmin against a synthetic oracle: the failure needs exactly two
    specific steps and no faults — the shrinker must strip everything
    else (steps, fault classes) and keep the invariant kind."""
    schedule = generate(0, 3, 40, FaultConfig.all_faults())
    needles = [Step("rotate", 2), Step("compact", 2)]
    schedule = schedule.with_steps(list(schedule.steps) + needles)

    class FakeResult:
        def __init__(self, violation):
            self.violation = violation

    def run_fn(s):
        has_rotate = any(
            st.kind == "rotate" and st.replica == 2 for st in s.steps
        )
        has_compact = any(
            st.kind == "compact" and st.replica == 2 for st in s.steps
        )
        if has_rotate and has_compact:
            return FakeResult(Violation("divergence", "synthetic"))
        return FakeResult(None)

    small, violation = shrink(
        schedule, Violation("divergence", "synthetic"), run_fn, max_runs=400
    )
    assert violation.invariant == "divergence"
    kinds = sorted((s.kind, s.replica) for s in small.steps)
    assert kinds == [("compact", 2), ("rotate", 2)]
    assert small.faults.enabled_classes() == []


def test_shrinker_rejects_different_invariant():
    """A candidate that fails a DIFFERENT invariant is a different bug:
    the shrinker must not accept it as a reduction."""
    schedule = generate(0, 3, 10, FaultConfig.none())
    marker = Step("rotate", 1)
    schedule = schedule.with_steps(list(schedule.steps) + [marker])

    class FakeResult:
        def __init__(self, violation):
            self.violation = violation

    def run_fn(s):
        # full schedule fails "divergence"; any reduction flips to
        # a "fsck" failure — nothing may shrink
        if len(s.steps) == len(schedule.steps):
            return FakeResult(Violation("divergence", "original"))
        return FakeResult(Violation("fsck", "decoy"))

    small, violation = shrink(
        schedule, Violation("divergence", "original"), run_fn, max_runs=60
    )
    assert violation.invariant == "divergence"
    assert len(small.steps) == len(schedule.steps)


# ------------------------------------------------------------ fleet scale
@pytest.mark.slow
def test_fleet_scale_every_fault_class():
    """ISSUE-9 acceptance: ≥8 replicas, ≥500 steps, every fault class
    enabled and actually firing, deterministically reproducible, all
    quiescence invariants held."""
    schedule = generate(42, 8, 500, FaultConfig.all_faults())
    result = run_schedule(schedule)
    assert result.ok, result.violation
    for cls in FaultConfig.CLASSES:
        assert result.fault_stats.get(cls, 0) > 0, f"{cls} never fired"
    assert result.service_cycles >= 1
    assert result.checks_run >= 1
    again = run_schedule(schedule)
    assert again.fingerprint == result.fingerprint

"""Randomized whole-stack simulation: N replicas, a random schedule of
writes / syncs / compactions / crashes, convergence at quiescence.

The strongest property the system claims — any interleaving of replica
activity over a passively synced directory converges to one state — gets
tested the way the architecture makes cheap (SURVEY.md §4): point many
cores at one shared remote tmpdir and drive them from a seeded RNG.  Byte
equality of canonical serialization across ALL replicas is the acceptance
bar, with both the host and the TPU (virtual-mesh) accelerator in the mix
so the two execution paths face the same histories.
"""

import asyncio
import uuid

import pytest

from crdt_enc_tpu.backends import FsStorage, IdentityCryptor, PlainKeyCryptor
from crdt_enc_tpu.core import Core, OpenOptions, orset_adapter
from crdt_enc_tpu.models import canonical_bytes
from crdt_enc_tpu.parallel import TpuAccelerator
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


def run(coro):
    return asyncio.run(coro)


def make_opts(tmp_path, name, accelerated=False):
    accel = {}
    if accelerated:
        a = TpuAccelerator(min_device_batch=1)
        accel = {"accelerator": a}
    return OpenOptions(
        storage=FsStorage(str(tmp_path / name), str(tmp_path / "remote")),
        cryptor=IdentityCryptor(),
        key_cryptor=PlainKeyCryptor(),
        adapter=orset_adapter(),
        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
        current_data_version=DEFAULT_DATA_VERSION_1,
        create=True,
        **accel,
    )


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_random_schedule_converges(tmp_path, seed):
    import numpy as np

    rng = np.random.default_rng(seed)
    N_REPLICAS = 4
    N_STEPS = 120
    MEMBERS = [f"item-{i}".encode() for i in range(12)]

    async def go():
        cores = [
            await Core.open(
                make_opts(tmp_path, f"r{i}", accelerated=(i % 2 == 1))
            )
            for i in range(N_REPLICAS)
        ]
        for _ in range(N_STEPS):
            i = int(rng.integers(N_REPLICAS))
            c = cores[i]
            action = rng.random()
            if action < 0.55:
                m = MEMBERS[int(rng.integers(len(MEMBERS)))]
                await c.update(lambda s, m=m: s.add_ctx(c.actor_id, m))
            elif action < 0.75:
                m = MEMBERS[int(rng.integers(len(MEMBERS)))]
                await c.update(
                    lambda s, m=m: s.rm_ctx(m) if s.contains(m) else None
                )
            elif action < 0.92:
                await c.read_remote()
            elif action < 0.97:
                await c.compact()
            else:
                # "crash" + rejoin: replace the core with a fresh open of
                # the same local dir (memory state rebuilt from the remote)
                cores[i] = await Core.open(
                    OpenOptions(
                        storage=FsStorage(
                            str(tmp_path / f"r{i}"), str(tmp_path / "remote")
                        ),
                        cryptor=IdentityCryptor(),
                        key_cryptor=PlainKeyCryptor(),
                        adapter=orset_adapter(),
                        supported_data_versions=(DEFAULT_DATA_VERSION_1,),
                        current_data_version=DEFAULT_DATA_VERSION_1,
                        create=False,
                    )
                )
                await cores[i].read_remote()

        # quiescence: two sync rounds so every replica sees every write
        # (a compact by X after Y's last read can strand Y one round behind)
        for _ in range(2):
            for c in cores:
                await c.read_remote()

        blobs = [c.with_state(canonical_bytes) for c in cores]
        assert all(b == blobs[0] for b in blobs), (
            "replicas diverged at quiescence"
        )

        # and one final compaction leaves a remote a newcomer joins from
        await cores[0].compact()
        fresh = await Core.open(make_opts(tmp_path, "newcomer"))
        await fresh.read_remote()
        assert fresh.with_state(canonical_bytes) == blobs[0]

    run(go())


@pytest.mark.parametrize("seed", [7, 8])
def test_random_schedule_converges_chunked_sessions(tmp_path, seed, monkeypatch):
    """The same convergence property with the ingest pipeline maximally
    stressed: tiny fs chunks and instant session promotion, so every
    accelerated sync runs multi-chunk host-reduce fold sessions instead
    of single-batch folds."""
    import crdt_enc_tpu.parallel.session as S
    from crdt_enc_tpu.backends.fs import FsStorage

    monkeypatch.setattr(S, "BUFFER_BYTES", 64)
    monkeypatch.setattr(FsStorage, "CHUNK_BYTES", 2048)
    test_random_schedule_converges(tmp_path, seed)

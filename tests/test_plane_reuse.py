"""Device-resident plane reuse (ISSUE 4): the accelerator keeps a fold's
result planes on device between rounds, so repeated ``read_remote`` /
``compact`` rounds in one process stop re-issuing the full-state
``device_put`` — provable via the ``h2d_bytes`` counter — while every
byte of every resulting state stays identical to the host reference.
Plus the CRDT_JIT_CACHE persistent-compilation-cache wiring.
"""

import asyncio

import numpy as np
import pytest

from crdt_enc_tpu.core.adapters import HostAccelerator
from crdt_enc_tpu.models import ORSet
from crdt_enc_tpu.models.orset import AddOp, RmOp
from crdt_enc_tpu.models.vclock import Dot, VClock
from crdt_enc_tpu.parallel import TpuAccelerator
from crdt_enc_tpu.utils import codec, trace

R, E = 16, 64
ACTORS = [bytes([i]) * 16 for i in range(R)]


def gen_ops(n, seed, clock):
    rng = np.random.default_rng(seed)
    ops = []
    for _ in range(n):
        a = ACTORS[int(rng.integers(R))]
        m = int(rng.integers(E))
        if rng.random() < 0.15 and clock.get(a, 0):
            ops.append(RmOp(m, VClock({a: clock[a]})))
        else:
            clock[a] = clock.get(a, 0) + 1
            ops.append(AddOp(m, Dot(a, clock[a])))
    return ops


def h2d():
    return trace.snapshot()["counters"].get("h2d_bytes", 0)


def states_equal(a, b):
    return codec.pack(a.to_obj()) == codec.pack(b.to_obj())


def test_round2_fold_reuses_device_planes():
    accel, host = TpuAccelerator(min_device_batch=1), HostAccelerator()
    s_acc, s_host, clock = ORSet(), ORSet(), {}
    trace.reset()
    ops = gen_ops(2000, 1, clock)
    accel.fold_ops(s_acc, ops)
    host.fold_ops(s_host, list(ops))
    plane_bytes = 4 * (R + 2 * E * R)
    assert h2d() >= plane_bytes  # round 1 uploads the state planes
    trace.reset()
    ops = gen_ops(2000, 2, clock)
    accel.fold_ops(s_acc, ops)
    host.fold_ops(s_host, list(ops))
    assert h2d() == 0, "round 2 re-uploaded state planes despite the cache"
    assert states_equal(s_acc, s_host)
    trace.reset()


def test_host_mutation_invalidates_plane_cache():
    accel, host = TpuAccelerator(min_device_batch=1), HostAccelerator()
    s_acc, s_host, clock = ORSet(), ORSet(), {}
    ops = gen_ops(1500, 3, clock)
    accel.fold_ops(s_acc, ops)
    host.fold_ops(s_host, list(ops))
    # a host-side apply lands between rounds (the cache MUST notice)
    clock[ACTORS[0]] += 1
    side = AddOp(E + 5, Dot(ACTORS[0], clock[ACTORS[0]]))
    s_acc.apply(side)
    s_host.apply(side)
    trace.reset()
    ops = gen_ops(1500, 4, clock)
    accel.fold_ops(s_acc, ops)
    host.fold_ops(s_host, list(ops))
    assert h2d() > 0, "stale device planes were trusted after a host apply"
    assert states_equal(s_acc, s_host)
    # …and the refreshed cache hits again on round 3
    trace.reset()
    ops = gen_ops(1500, 5, clock)
    accel.fold_ops(s_acc, ops)
    host.fold_ops(s_host, list(ops))
    assert h2d() == 0
    assert states_equal(s_acc, s_host)
    trace.reset()


def test_plane_cache_grows_with_vocab():
    """Round 2 introduces members AND actors the cache has never seen:
    the cached planes must pad on device and stay byte-correct."""
    accel, host = TpuAccelerator(min_device_batch=1), HostAccelerator()
    s_acc, s_host, clock = ORSet(), ORSet(), {}
    ops = gen_ops(1000, 6, clock)
    accel.fold_ops(s_acc, ops)
    host.fold_ops(s_host, list(ops))
    extra = [bytes([100 + i]) * 16 for i in range(5)]
    ops2 = []
    for i, a in enumerate(extra):
        for k in range(40):
            clock[a] = clock.get(a, 0) + 1
            ops2.append(AddOp(E + 50 + (k % 30), Dot(a, clock[a])))
    ops2.extend(gen_ops(500, 7, clock))
    trace.reset()
    accel.fold_ops(s_acc, ops2)
    host.fold_ops(s_host, list(ops2))
    assert h2d() == 0, "vocab growth fell off the cached-plane path"
    assert states_equal(s_acc, s_host)
    trace.reset()


def test_plane_reuse_off_switch(monkeypatch):
    monkeypatch.setenv("CRDT_PLANE_REUSE", "0")
    accel = TpuAccelerator(min_device_batch=1)
    assert not accel.plane_reuse
    s, clock = ORSet(), {}
    accel.fold_ops(s, gen_ops(800, 8, clock))
    trace.reset()
    accel.fold_ops(s, gen_ops(800, 9, clock))
    assert h2d() >= 4 * (R + 2 * E * R), "opt-out still cached planes"
    trace.reset()


def test_two_round_compact_product_path():
    """The ISSUE-4 acceptance shape through the REAL product path:
    compact → pipelined session (BUFFER) → dense fold.  Round 2's obs
    snapshot shows zero full-state h2d re-upload, and the state equals
    a cold host replica's."""
    from crdt_enc_tpu.backends import (
        IdentityCryptor, MemoryRemote, MemoryStorage, PlainKeyCryptor,
    )
    from crdt_enc_tpu.core import Core, OpenOptions, orset_adapter
    from crdt_enc_tpu.models import canonical_bytes
    from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

    def opts(storage, accel=None):
        return OpenOptions(
            storage=storage, cryptor=IdentityCryptor(),
            key_cryptor=PlainKeyCryptor(), adapter=orset_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1, create=True,
            accelerator=accel
            if accel is not None
            else TpuAccelerator(min_device_batch=1),
        )

    async def go():
        remote = MemoryRemote()
        reader = await Core.open(opts(MemoryStorage(remote)))
        writer = await Core.open(
            opts(MemoryStorage(remote), HostAccelerator())
        )

        async def write(n, tag):
            for i in range(n):
                await writer.apply_ops([writer.with_state(
                    lambda s: s.add_ctx(writer.actor_id, b"%s-%d" % (tag, i))
                )])

        await write(60, b"r1")
        trace.reset()
        await reader.compact()
        r1 = h2d()
        await write(60, b"r2")
        trace.reset()
        await reader.compact()
        r2 = h2d()
        trace.reset()
        assert r1 > 0, "round 1 should upload the state planes"
        assert r2 == 0, f"round 2 re-uploaded {r2} bytes"
        cold = await Core.open(
            opts(MemoryStorage(remote), HostAccelerator())
        )
        await cold.read_remote()
        assert reader.with_state(canonical_bytes) == cold.with_state(
            canonical_bytes
        )

    asyncio.run(go())


def test_device_stream_seeds_planes_on_device(monkeypatch):
    """DEVICE_STREAM promotion creates its zero accumulator planes ON
    device (XLA fill) — no plane-sized host buffer is uploaded, so
    h2d_bytes carries only the op chunks."""
    from crdt_enc_tpu.parallel import session as S

    monkeypatch.setattr(S, "BUFFER_BYTES", 0)
    monkeypatch.setattr(S, "HOST_PLANE_CELLS", -1)
    accel = TpuAccelerator(min_device_batch=1)
    state, clock = ORSet(), {}
    ops = gen_ops(1200, 10, clock)
    payload = [codec.pack([op.to_obj() for op in ops[i : i + 24]])
               for i in range(0, len(ops), 24)]
    session = accel.open_fold_session(state, actors_hint=ACTORS)
    trace.reset()
    session.feed(payload)
    assert session.mode == "device_stream"
    plane_bytes = 4 * (session.R + 2 * session._d_E * session.R)
    assert h2d() < plane_bytes, (
        "device-stream promotion uploaded plane-sized zero buffers"
    )
    session.finish()
    trace.reset()
    host_state = ORSet()
    HostAccelerator().fold_ops(host_state, list(ops))
    assert states_equal(state, host_state)


def test_jit_cache_second_instance_recompiles_nothing(tmp_path, monkeypatch):
    """CRDT_JIT_CACHE wires jax's persistent compilation cache: after a
    simulated process restart (jax.clear_caches), a second accelerator
    instance serves every compile request it can from the disk cache —
    zero new jax_cache_misses."""
    import jax

    from crdt_enc_tpu.obs import runtime

    cache_dir = str(tmp_path / "jit-cache")
    monkeypatch.setenv("CRDT_JIT_CACHE", cache_dir)
    runtime.track_recompiles()

    def fold_once():
        accel = TpuAccelerator(min_device_batch=1)  # wires the cache dir
        # CPU compiles are sub-second: persist them all for the test
        # (the constructor's enable_compilation_cache resets the floor)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
        s, clock = ORSet(), {}
        rng = np.random.default_rng(42)  # identical batch both runs
        ops = []
        for _ in range(600):
            a = ACTORS[int(rng.integers(R))]
            clock[a] = clock.get(a, 0) + 1
            ops.append(AddOp(int(rng.integers(E)), Dot(a, clock[a])))
        accel.fold_ops(s, ops)
        return s

    prev_min = jax.config.jax_persistent_cache_min_compile_time_secs
    try:
        # earlier tests may have compiled these very shapes: drop the
        # in-memory jit cache so run 1 really compiles (into the fresh
        # cache dir, so they are misses)
        jax.clear_caches()
        fold_once()  # real compiles, all persisted to the cache dir
        first_misses = trace.snapshot()["counters"].get(
            "jax_cache_misses", 0
        )
        assert first_misses > 0, "first run should miss the empty cache"
        jax.clear_caches()  # simulate a fresh process
        before = trace.snapshot()["counters"]
        fold_once()
        after = trace.snapshot()["counters"]
        new_misses = after.get("jax_cache_misses", 0) - before.get(
            "jax_cache_misses", 0
        )
        new_hits = after.get("jax_cache_hits", 0) - before.get(
            "jax_cache_hits", 0
        )
        assert new_misses == 0, (
            f"{new_misses} compiles missed the persistent cache"
        )
        assert new_hits > 0, "nothing was served from the persistent cache"
    finally:
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", prev_min
        )
        jax.config.update("jax_compilation_cache_dir", None)
        trace.reset()

"""PARITY.md's published test count must match the collected suite.

VERDICT r4 weak item 5: the documented count drifted two rounds in a
row (392→396→404).  The count in docs/PARITY.md row 12 is now asserted
against the live collection; regenerate it with
``python tools/update_parity_count.py`` after adding tests.

The assertion only engages on FULL-suite runs — a subset invocation
(``pytest tests/test_x.py``) collects fewer test files and must not
false-fail — detected by comparing the number of collected test files
against the ``test_*.py`` files on disk.
"""

import pathlib
import re

ROOT = pathlib.Path(__file__).resolve().parent.parent
PARITY = ROOT / "docs" / "PARITY.md"
COUNT_RE = re.compile(r"`tests/` — (\d+) tests")


def parity_count() -> int:
    m = COUNT_RE.search(PARITY.read_text())
    assert m, "docs/PARITY.md row 12 lost its '`tests/` — N tests' marker"
    return int(m.group(1))


def test_parity_count_matches_collection(request):
    import pytest

    # collection info travels on the pytest config (conftest stashes it in
    # pytest_configure) — importing conftest directly would break under
    # --import-mode=importlib (ADVICE r5)
    COLLECT_INFO = request.config.crdt_collect_info

    n_disk_files = len(list((ROOT / "tests").glob("test_*.py")))
    if COLLECT_INFO["n_files"] != n_disk_files:
        pytest.skip(
            f"subset run ({COLLECT_INFO['n_files']} of {n_disk_files} "
            "test files collected); the count assertion needs the full "
            "suite"
        )
    if COLLECT_INFO["n_deselected"]:
        pytest.skip(
            f"{COLLECT_INFO['n_deselected']} tests deselected (-k/-m); "
            "the count assertion needs the full suite"
        )
    documented = parity_count()
    collected = COLLECT_INFO["n_items"]
    assert documented == collected, (
        f"docs/PARITY.md says {documented} tests but the suite collects "
        f"{collected}; run python tools/update_parity_count.py"
    )

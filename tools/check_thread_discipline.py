#!/usr/bin/env python
"""Lint shim: no bare ``threading.Thread`` outside the ingest pipeline.

The check itself moved into the static-analysis engine as rule THR001
(crdt_enc_tpu/analysis/rules/threads.py); the old per-file allowlist
with pinned site counts became ``max``-pinned entries in
tools/analysis_baseline.toml — same semantics: a NEW bare thread in an
allowlisted file exceeds the pin and fails.  This shim keeps the
historical CLI and exit codes (0 clean, 1 violations); prefer
``python -m crdt_enc_tpu.tools.analyze --rule THR001``.
"""

from __future__ import annotations

import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent


def main(argv=None) -> int:
    sys.path.insert(0, str(ROOT))
    from crdt_enc_tpu.analysis.cli import main as analyze

    return analyze(["--rule", "THR001", "--root", str(ROOT)])


if __name__ == "__main__":
    raise SystemExit(main())

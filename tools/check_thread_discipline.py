#!/usr/bin/env python
"""Lint: no bare ``threading.Thread`` construction outside the ingest
pipeline.

Ad-hoc threads bypass everything the fan-out pipeline guarantees —
backpressure (the BoundedSemaphore memory bound), ordered sequencing,
fault propagation (first failure cancels the peers, threads are joined),
and per-lane observability (numbered producer lanes, the
``stream_producers`` gauge).  Every parallel ingest in library code must
therefore go through ``ops/stream.py run_ingest_pipeline``; the few
legitimate exceptions are enumerated in :data:`ALLOWED` with the reason
they are not ingest work.

Scans ``crdt_enc_tpu/``, ``benchmarks/``, and ``examples/`` for
``threading.Thread(`` call sites (``bench.py``'s watchdog is a
measurement-harness guard, also allowlisted).  Exits 1 on any
non-allowlisted site.  Run directly or via the tier-1 suite
(tests/test_obs.py).
"""

from __future__ import annotations

import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent

SCAN_GLOBS = [
    ("crdt_enc_tpu", "**/*.py"),
    ("benchmarks", "**/*.py"),
    ("examples", "**/*.py"),
    (".", "bench.py"),
]

# path (repo-relative, posix) -> (max Thread call sites, reason).  The
# count is pinned so a NEW bare thread added to an allowlisted file still
# fails — the allowlist covers the existing sites, not the whole file.
ALLOWED = {
    "crdt_enc_tpu/ops/stream.py": (
        1, "run_ingest_pipeline itself — the one sanctioned producer pool"
    ),
    "crdt_enc_tpu/backends/gpg_keys.py": (
        1, "stderr drain of a gpg subprocess; no ingest work, no backpressure"
    ),
    "bench.py": (
        1, "backend-init watchdog: force-exits a hung TPU-tunnel probe"
    ),
}

THREAD_RE = re.compile(r"\bthreading\.Thread\(")


def scan():
    """Yield (relpath, lineno) for every threading.Thread( call site."""
    for base, pattern in SCAN_GLOBS:
        for path in sorted((ROOT / base).glob(pattern)):
            rel = path.relative_to(ROOT).as_posix()
            for lineno, line in enumerate(
                path.read_text().splitlines(), start=1
            ):
                if THREAD_RE.search(line):
                    yield rel, lineno


def main(argv=None) -> int:
    errors = 0
    counts: dict[str, list[int]] = {}
    for rel, lineno in scan():
        if rel in ALLOWED:
            counts.setdefault(rel, []).append(lineno)
            continue
        print(
            f"ERROR {rel}:{lineno}: bare threading.Thread outside "
            "run_ingest_pipeline — route parallel ingest through "
            "ops/stream.py (or add an ALLOWED entry with a reason)"
        )
        errors += 1
    for rel, linenos in sorted(counts.items()):
        limit = ALLOWED[rel][0]
        if len(linenos) > limit:
            print(
                f"ERROR {rel}: {len(linenos)} Thread call sites at lines "
                f"{linenos}, allowlist covers only {limit} — a new bare "
                "thread was added to an allowlisted file"
            )
            errors += 1
    for rel in sorted(set(ALLOWED) - set(counts)):
        print(f"WARN allowlist entry `{rel}` has no Thread call site")
    if errors:
        print(f"{errors} undisciplined thread site(s)", file=sys.stderr)
        return 1
    n_sites = sum(len(v) for v in counts.values())
    print(f"OK: {n_sites} allowlisted site(s), no bare threads")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

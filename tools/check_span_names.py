#!/usr/bin/env python
"""Lint shim: span/metric names vs the observability registry.

The check itself moved into the static-analysis engine as rule SPN001
(crdt_enc_tpu/analysis/rules/spans.py — same invariants: every literal
``trace.span/add/gauge/observe`` name registered in
docs/observability.md, registered ``stream.*`` proof spans must have a
call site).  This shim keeps the historical CLI and exit codes (0 clean,
1 violations) for existing invocations; prefer
``python -m crdt_enc_tpu.tools.analyze --rule SPN001``.
"""

from __future__ import annotations

import pathlib
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent


def main(argv=None) -> int:
    sys.path.insert(0, str(ROOT))
    from crdt_enc_tpu.analysis.cli import main as analyze

    return analyze(["--rule", "SPN001", "--root", str(ROOT)])


if __name__ == "__main__":
    raise SystemExit(main())

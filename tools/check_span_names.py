#!/usr/bin/env python
"""Lint: every span/metric name used by library code must be registered.

The observability registry lives in docs/observability.md (the two
tables under "## Span registry" and "## Counter & gauge registry").
This script greps the tree for literal ``trace.span(`` / ``trace.add(``
/ ``trace.gauge(`` / ``trace.observe(`` call sites (plus ``record.*``,
the obs-internal spelling) and fails when

* a name used in code is missing from the registry (undocumented
  metric), or
* a call site passes a *dynamic* (f-string) name — names key the
  aggregate table and must stay low-cardinality literals.

Registry entries no longer present in code are reported as warnings
(stale doc rows) without failing, so conditionally-compiled call sites
don't break CI — EXCEPT the ``stream.*`` pipeline family (which
includes the fan-out's ``stream.producer.*`` lanes): those spans are
load-bearing for the overlap/backpressure proofs the streaming tests
and ``obs_report --check-overlap`` read, so a registered ``stream.*``
name with no call site is an ERROR (the proof would silently read an
empty timeline).  ``tests/`` is exempt (scratch names).  Run directly
or via the tier-1 suite (tests/test_obs.py).
"""

from __future__ import annotations

import pathlib
import re
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
DOC = ROOT / "docs" / "observability.md"

SCAN_GLOBS = [
    ("crdt_enc_tpu", "**/*.py"),
    ("benchmarks", "**/*.py"),
    ("examples", "**/*.py"),
    (".", "bench.py"),
]

CALL_RE = re.compile(
    r"\b(?:trace|record|_record)\.(span|add|gauge|observe)\(\s*"
    r"(?:(f)?(['\"])([^'\"]+)\3|([A-Za-z_][\w.]*))"
)

TABLE_ROW_RE = re.compile(r"^\|\s*`([^`]+)`\s*\|")


REGISTRY_SECTIONS = ("## Span registry", "## Counter & gauge registry")


def registry_names() -> set[str]:
    """Backticked first-column names from the registry tables ONLY —
    other tables in the doc (module overview etc.) are not a registry."""
    names: set[str] = set()
    in_registry = False
    for line in DOC.read_text().splitlines():
        if line.startswith("## "):
            in_registry = line.strip() in REGISTRY_SECTIONS
            continue
        if not in_registry:
            continue
        m = TABLE_ROW_RE.match(line)
        if m:
            names.add(m.group(1))
    return names


def scan_calls():
    """Yield (path, lineno, kind, name, dynamic) for every call site."""
    for base, pattern in SCAN_GLOBS:
        for path in sorted((ROOT / base).glob(pattern)):
            rel = path.relative_to(ROOT)
            text = path.read_text()
            for m in CALL_RE.finditer(text):
                lineno = text.count("\n", 0, m.start()) + 1
                kind, fpref, _q, literal, ident = (
                    m.group(1), m.group(2), m.group(3), m.group(4),
                    m.group(5),
                )
                if literal is not None and not fpref:
                    yield rel, lineno, kind, literal, False
                else:
                    yield rel, lineno, kind, ident or literal, True


def main(argv=None) -> int:
    if not DOC.exists():
        print(f"missing registry doc: {DOC}", file=sys.stderr)
        return 1
    registered = registry_names()
    if not registered:
        print("docs/observability.md has no registry tables", file=sys.stderr)
        return 1
    used: set[str] = set()
    errors = 0
    for rel, lineno, kind, name, dynamic in scan_calls():
        if dynamic:
            # a variable name is fine when the VALUES are registered
            # literals defined nearby; flag only f-strings (true dynamic
            # cardinality) — identifiers get a warning
            print(f"WARN {rel}:{lineno}: non-literal {kind} name ({name})")
            continue
        used.add(name)
        if name not in registered:
            print(
                f"ERROR {rel}:{lineno}: {kind}(\"{name}\") is not in the "
                "docs/observability.md registry"
            )
            errors += 1
    # names maintained inside obs.record itself (no trace.* call site)
    internal = {"events_dropped"}
    # the streaming-pipeline family backs machine-checked proofs
    # (chunk_overlaps, the seam/backpressure tests): a registered
    # stream.* name that nothing emits means a proof reads nothing
    PROOF_PREFIXES = ("stream.",)
    for stale in sorted(registered - used - internal):
        if stale.startswith(PROOF_PREFIXES):
            print(
                f"ERROR registry entry `{stale}` ({PROOF_PREFIXES[0]}* "
                "family) has no literal call site — the overlap proofs "
                "would read an empty timeline"
            )
            errors += 1
            continue
        print(f"WARN registry entry `{stale}` has no literal call site")
    if errors:
        print(
            f"{errors} registry violation(s) — unregistered names and/or "
            "call-site-less stream.* proof spans, see ERROR lines",
            file=sys.stderr,
        )
        return 1
    print(f"OK: {len(used)} names used, all registered")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env bash
# Single entry point for the repo's correctness tooling (docs/static_analysis.md).
#
#   tools/run_checks.sh            # analysis + shims + parity count check
#
# Exit non-zero on the first failing check.  The same gates run from
# tier-1 via tests/test_static_analysis.py (engine clean on live repo)
# and tests/test_parity_count.py (doc count matches collection).

set -euo pipefail
cd "$(dirname "$0")/.."

echo "== static analysis (all rules, baseline diff) =="
python -m crdt_enc_tpu.tools.analyze --diff-baseline

echo "== span-name registry shim =="
python tools/check_span_names.py

echo "== thread-discipline shim =="
python tools/check_thread_discipline.py

echo "== adversarial sim smoke (bounded) + fixture replay =="
# one all-faults schedule with the full invariant check (~5s incl. jax
# import), then every committed shrunk-failure fixture — a regressed
# fixture fails the build (docs/simulation.md)
JAX_PLATFORMS=cpu python -m crdt_enc_tpu.tools.sim run \
    --seed 0 --replicas 4 --steps 80 --faults all
JAX_PLATFORMS=cpu python -m crdt_enc_tpu.tools.sim replay tests/data/sim

echo "== delta-enabled sim smoke (bounded) =="
# the same all-faults envelope with delta-state replication on and the
# dseal/dread/dgc vocabulary in play (docs/delta.md)
JAX_PLATFORMS=cpu python -m crdt_enc_tpu.tools.sim run \
    --seed 0 --replicas 4 --steps 80 --faults all --deltas

echo "== strong-read sim smoke (bounded) =="
# the read_strong/await_stable vocabulary + the linearizability checker
# under the all-faults envelope: every strong read is oracle-compared
# to the fold of exactly the cut it names (docs/strong_reads.md); the
# fixture replay above re-runs any committed shrunk failures
JAX_PLATFORMS=cpu python -m crdt_enc_tpu.tools.sim run \
    --seed 0 --replicas 4 --steps 80 --faults all --strong-reads

echo "== daemon-enabled sim smoke (bounded) =="
# a persistent FleetDaemon cycles INSIDE the all-fault schedule
# (daemon/ddrain vocabulary): crash/reopen, torn reads and delayed
# visibility hit the control plane too, and the five quiescence
# invariants check it like any replica (docs/multitenant.md)
JAX_PLATFORMS=cpu python -m crdt_enc_tpu.tools.sim run \
    --seed 0 --replicas 4 --steps 80 --faults all --daemon

echo "== combined sim smoke: daemon + deltas + strong reads (bounded) =="
# the ISSUE-16 acceptance envelope: continuation-enabled serve cycles,
# delta-state replication, and linearizable reads all inside ONE
# all-fault schedule — the vocabularies compose, and the quiescence
# invariants check the combination
JAX_PLATFORMS=cpu python -m crdt_enc_tpu.tools.sim run \
    --seed 0 --replicas 4 --steps 80 --faults all \
    --deltas --strong-reads --daemon

echo "== population sim smoke (bounded, serial-equality asserted) =="
# ISSUE-18: a small all-faults population through the ONE shared
# substrate, then every schedule re-run serially — any fingerprint or
# fault-tally divergence fails the build (the determinism law,
# docs/simulation.md "Population runs")
JAX_PLATFORMS=cpu python - <<'EOF'
from crdt_enc_tpu.sim import (
    FaultConfig, generate, run_population, verify_serial_equality,
)

schedules = [
    generate(seed, 4, 100, FaultConfig.all_faults(), members=6,
             deltas=True, daemon=True, strong_reads=True)
    for seed in range(4)
]
report = run_population(schedules, population=4)
bad = [(s.seed, r.violation) for s, r in
       zip(report.schedules, report.results) if not r.ok]
if bad:
    raise SystemExit(f"population smoke violations: {bad}")
problems = verify_serial_equality(report)
if problems:
    raise SystemExit(
        "population diverged from serial twins:\n  " + "\n  ".join(problems)
    )
fired = set()
for r in report.results:
    fired.update(k for k, v in r.fault_stats.items() if v)
missing = set(FaultConfig.CLASSES) - fired
if missing:
    raise SystemExit(f"population smoke never fired fault classes: {missing}")
print(f"OK: {len(schedules)} schedules, population 4, "
      f"wall {report.wall_s:.1f}s, serial-equal, all fault classes fired")
EOF

echo "== daemon smoke: faulted cycles -> drain -> fsck =="
# bounded always-on daemon selftest: an in-memory fleet with injected
# tenant faults runs supervised cycles (errors must isolate into
# backoff/quarantine while healthy tenants keep sealing), heals,
# recovers, drains, and every remote must fsck clean + refold solo
JAX_PLATFORMS=cpu python -m crdt_enc_tpu.tools.daemon selftest \
    --tenants 6 --cycles 6 --faulty 2

echo "== sharded-serve smoke (8 virtual devices) =="
# the mesh-backed FoldService path on the virtual 8-device CPU mesh
# (docs/multitenant.md "Sharding the fleet across a pod"): faulted
# daemon cycles through the sharded mega-folds, drain, fsck, and the
# cold-refold byte-identity assert — so the mesh path cannot rot on
# CPU-only boxes
XLA_FLAGS="--xla_force_host_platform_device_count=8" JAX_PLATFORMS=cpu \
    python -m crdt_enc_tpu.tools.daemon selftest \
    --tenants 6 --cycles 4 --faulty 2 --mesh dp=8

echo "== delta-vs-snapshot differential gate =="
# chained delta consumers must be byte-identical to full-snapshot
# consumers across adapters (incl. the composed resettable counter)
# and both storage backends (docs/delta.md)
JAX_PLATFORMS=cpu python -m pytest tests/test_delta.py -q \
    -p no:cacheprovider -k "differential or rides_device_kernels"

echo "== idle-cycle gate (O(tail) steady state) =="
# a quiet tenant's steady-state cycle must be an honest no-op: zero
# XLA compiles, zero state H2D, zero storage probes beyond the listing
# (spy-pinned), and the committed --e2e-idle-cycle record must hold the
# >=10x bar at 1% active (docs/multitenant.md "The cycle-cost law")
JAX_PLATFORMS=cpu python -m pytest tests/test_continuation.py -q \
    -p no:cacheprovider \
    -k "quiet_steady_state or idle_cycle_metric or device_cut_cycle"

echo "== obs_report fleet golden =="
# the SLO column follows the active CRDT_SLO_* config by design — pin
# the defaults here so the golden diff is environment-insensitive
env -u CRDT_SLO_FRESHNESS_LAG -u CRDT_SLO_OBJECTIVE \
    python -m crdt_enc_tpu.tools.obs_report fleet \
    tests/data/fleet_device_a.jsonl tests/data/fleet_device_b.jsonl \
    | diff -u tests/data/obs_fleet_golden.txt - \
    || { echo "fleet rendering drifted from tests/data/obs_fleet_golden.txt"; exit 1; }

echo "== perf trend ratchet (BENCH_LOCAL) =="
# nightly perf ratchet (ROADMAP item 5): a config whose latest run
# dropped >45% below its prior best fails the build.  45% tolerates
# the documented ±30% shared-box swing (docs/multitenant.md) while
# catching real order-of-magnitude regressions.
python -m crdt_enc_tpu.tools.obs_report trend BENCH_LOCAL.jsonl \
    --fail-on-regression 45

echo "== parity count =="
python - <<'EOF'
import pathlib
import re
import sys

sys.path.insert(0, "tools")
from update_parity_count import COUNT_RE, PARITY, collected_count

doc = COUNT_RE.search(PARITY.read_text())
live = collected_count()
if doc is None:
    raise SystemExit("docs/PARITY.md row 12 lost its test-count marker")
if int(doc.group(2)) != live:
    raise SystemExit(
        f"docs/PARITY.md says {doc.group(2)} tests, collection says {live} "
        "— run tools/update_parity_count.py"
    )
print(f"OK: {live} tests")
EOF

echo "== all checks passed =="

"""Regenerate the test count in docs/PARITY.md row 12 from a live
``pytest --collect-only`` (the count is asserted by
tests/test_parity_count.py on every full suite run)."""

import pathlib
import re
import subprocess
import sys

ROOT = pathlib.Path(__file__).resolve().parent.parent
PARITY = ROOT / "docs" / "PARITY.md"
COUNT_RE = re.compile(r"(`tests/` — )(\d+)( tests)")


def collected_count() -> int:
    r = subprocess.run(
        [sys.executable, "-m", "pytest", "tests/", "--collect-only", "-q"],
        cwd=ROOT, capture_output=True, text=True,
    )
    if r.returncode != 0:
        raise SystemExit(
            f"collection failed (rc={r.returncode}) — refusing to write "
            f"a partial count:\n{r.stdout[-2000:]}"
        )
    m = re.search(r"(\d+) tests collected", r.stdout)
    if not m:
        raise SystemExit(f"could not parse collection output:\n{r.stdout[-2000:]}")
    if re.search(r"\berrors?\b", r.stdout.splitlines()[-1] if r.stdout else ""):
        raise SystemExit(
            f"collection reported errors — refusing to write a partial "
            f"count:\n{r.stdout[-2000:]}"
        )
    return int(m.group(1))


def main():
    n = collected_count()
    text = PARITY.read_text()
    new, subs = COUNT_RE.subn(rf"\g<1>{n}\g<3>", text)
    if not subs:
        raise SystemExit("PARITY.md row 12 lost its test-count marker")
    PARITY.write_text(new)
    print(f"docs/PARITY.md test count -> {n}")


if __name__ == "__main__":
    main()

"""North-star benchmark: OR-Set compaction fold, TPU vs single-core host.

Config #3 from BASELINE.md — 10k replicas / 1M add+remove ops — folded by
the jitted ``orset_fold`` kernel (the TPU replacement for the reference's
per-op host loop, crdt-enc/src/lib.rs:533-539).  The single-core baseline
is this repo's host-reference ORSet (identical semantics, verified
byte-identical on a subsample here and exhaustively in tests/).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
value = TPU ops merged/sec (post-compile); vs_baseline = speedup over the
single-core host fold (host rate measured on a capped subsample of the
same op stream — the host loop is O(n), so the per-op rate transfers).

Timing method: the TPU in this environment is reached through a tunnel
with a ~100ms fixed round-trip per dispatch+sync — pure client latency,
unrelated to device compute (a trivial scalar jit call costs the same
100ms).  Per-fold device time is therefore measured as the MARGINAL cost
of one fold inside a K-chained ``lax.scan`` (time(K=1+CHAIN) − time(K=1))
/ CHAIN — the chain carries the state planes through each fold, so no
iteration can be elided; the fixed latency cancels in the subtraction.
Single-dispatch wall-clock (latency included) is logged to stderr too.

Env knobs: BENCH_OPS (1_000_000), BENCH_REPLICAS (10_000),
BENCH_MEMBERS (4096), BENCH_HOST_OPS (100_000), BENCH_ITERS (3),
BENCH_CHAIN (20).
"""

from __future__ import annotations

import datetime
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np


def log(*a):
    print(*a, file=sys.stderr, flush=True)


REPO_ROOT = os.path.dirname(os.path.abspath(__file__))
# Every successful run appends its full per-variant record here (committed),
# so one capture-time tunnel outage cannot erase a round's perf evidence —
# the round-3 failure mode (BENCH_r03.json: rc=1, parsed null, while the
# kernel's numbers had been observed in-round with nothing persisted).
LOCAL_LOG = os.path.join(REPO_ROOT, "BENCH_LOCAL.jsonl")


def _append_local(rec: dict) -> None:
    try:
        line = json.dumps(rec)  # serialize before touching the file
        with open(LOCAL_LOG, "a") as f:
            f.write(line + "\n")
    except (OSError, TypeError, ValueError) as e:
        # never let bookkeeping kill a good run
        log(f"WARNING: could not append {LOCAL_LOG}: {e!r}")


def _last_good_local():
    """Most recent successful record from BENCH_LOCAL.jsonl, or None."""
    try:
        with open(LOCAL_LOG) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError:
        return None
    for ln in reversed(lines):
        try:
            rec = json.loads(ln)
        except ValueError:
            continue  # e.g. a truncated final append from a killed run
        if rec.get("value") and rec.get("backend") == "tpu":
            return rec
    return None


def _fail_unavailable(stage: str, attempts: list) -> "NoReturn":
    """Distinguishable failure: ONE diagnostic JSON line on stdout (value
    null, error field, probe history, last persisted good run) + exit 3.
    Consumers can reconcile the null against BENCH_LOCAL.jsonl."""
    print(json.dumps({
        "metric": "orset_compaction_fold_ops_per_sec",
        "value": None,
        "unit": "ops/s",
        "vs_baseline": None,
        "error": "tpu_backend_unavailable",
        "stage": stage,
        "attempts": attempts,
        "last_good_local": _last_good_local(),
    }), flush=True)
    # os._exit: the hung backend-init thread (if any) must not block exit
    os._exit(3)


def acquire_jax(want_tpu: bool):
    """Backend acquisition that cannot hang the bench.

    Round 3 lost its perf artifact to exactly this: ``jax.devices()``
    either failed fast with UNAVAILABLE or hung >9 minutes when the TPU
    tunnel was down, and bench.py had no defense.  Strategy:

    1. Probe backend init in a SUBPROCESS under a hard timeout
       (``BENCH_INIT_TIMEOUT``, default 90s), with ``BENCH_INIT_ATTEMPTS``
       retries (default 4) and ``BENCH_INIT_BACKOFF``s between (default
       45) — a flaky tunnel gets several minutes to come back without any
       risk of wedging this process.
    2. Only then init in-process, with a watchdog thread that force-exits
       (same diagnostic JSON, exit 3) if init exceeds 3× the timeout —
       a probe success followed by an in-process hang still terminates.

    When the caller doesn't expect a TPU (JAX_PLATFORMS=cpu — tests,
    smoke runs), skip the probe entirely.
    """
    if not want_tpu:
        import jax

        return jax, jax.devices()[0]

    timeout = float(os.environ.get("BENCH_INIT_TIMEOUT", 90))
    n_attempts = int(os.environ.get("BENCH_INIT_ATTEMPTS", 4))
    backoff = float(os.environ.get("BENCH_INIT_BACKOFF", 45))
    probe_src = (
        "import jax; d = jax.devices()[0]; print(d.platform, d.device_kind)"
    )
    attempts = []
    for i in range(n_attempts):
        t0 = time.perf_counter()
        try:
            r = subprocess.run(
                [sys.executable, "-c", probe_src],
                capture_output=True, text=True, timeout=timeout,
            )
            out = r.stdout.strip()
            if not out:
                tail = r.stderr.strip().splitlines()
                out = tail[-1] if tail else ""
            rec = {
                "rc": r.returncode,
                "secs": round(time.perf_counter() - t0, 1),
                "out": out[:200],
            }
        except subprocess.TimeoutExpired:
            rec = {"rc": "timeout",
                   "secs": round(time.perf_counter() - t0, 1), "out": ""}
        attempts.append(rec)
        ok = rec["rc"] == 0 and "tpu" in str(rec["out"]).lower()
        log(f"backend probe {i + 1}/{n_attempts}: {rec}")
        if ok:
            break
        if i + 1 < n_attempts:
            time.sleep(backoff)
    else:
        _fail_unavailable("subprocess_probe", attempts)

    done = threading.Event()

    def watchdog():
        if not done.wait(3 * timeout):
            log("in-process backend init exceeded watchdog; aborting")
            _fail_unavailable("in_process_init_hang", attempts)

    threading.Thread(target=watchdog, daemon=True).start()
    try:
        import jax

        dev = jax.devices()[0]
    except Exception as e:  # fast UNAVAILABLE after a good probe (flap)
        log(f"in-process backend init failed: {e!r}")
        done.set()
        _fail_unavailable("in_process_init_error", attempts)
    done.set()
    return jax, dev


# Pinned host-baseline protocol (single source of truth — suite.py imports
# these): the 1-core per-op loop on this box shows ±30% run-to-run spread,
# so no speedup may rest on a single host sample.  Host baselines are the
# MEDIAN of BENCH_HOST_RUNS (default 5) with raw samples published; device
# times stay best-of (their marginal-chain timing is low-noise and
# interference is one-sided), an asymmetry stated in BASELINE.md — the
# recorded samples let anyone recompute a min-based ratio.
HOST_RUNS = int(os.environ.get("BENCH_HOST_RUNS", 5))


def host_median(run_once, n: int = 0):
    """Median-of-n host baseline.  ``run_once`` returns (seconds, payload);
    returns (median_seconds, sorted_samples, first_payload) — the payload
    (usually the folded host state) feeds byte-equality checks."""
    n = n or HOST_RUNS
    runs = [run_once() for _ in range(n)]
    times = sorted(t for t, _ in runs)
    return times[n // 2], times, runs[0][1]


def host_stats(times: list) -> dict:
    """The protocol's reporting fields for a result record."""
    med = times[len(times) // 2]
    return dict(
        host_samples_s=[round(t, 4) for t in times],
        host_spread_pct=round(100.0 * (times[-1] - times[0]) / med, 1),
    )


# Canonical pinned host baselines (VERDICT r4 weak items 1/6): same-run
# host rates swing 1.5× with machine weather even under the median-of-5
# protocol, so published ratios use ONE committed idle-box measurement
# per config (benchmarks/pinned_baselines.json, written by
# benchmarks/pin_baselines.py with raw samples).  Same-run rates are
# still recorded for drift detection.
PINNED_PATH = os.path.join(REPO_ROOT, "benchmarks", "pinned_baselines.json")


def load_pinned(config: str, shape: dict):
    """The pinned host record for ``config``, or None when absent or
    measured at a different workload shape (ratios across shapes would
    be meaningless — e.g. smoke runs)."""
    try:
        with open(PINNED_PATH) as f:
            pins = json.load(f)
    except (OSError, ValueError):
        return None
    rec = pins.get(config)
    if not rec or rec.get("shape") != shape:
        return None
    return rec


def pinned_ratio_fields(config: str, shape: dict, device_rate: float,
                        same_run_ratio: float) -> dict:
    """vs_baseline resolution: the pinned ratio when a matching pin
    exists (the stable denominator of record), same-run otherwise —
    with both always recorded explicitly."""
    rec = load_pinned(config, shape)
    out = {"vs_same_run_host": round(same_run_ratio, 2)}
    if rec:
        raw = device_rate / rec["host_rate"]
        out["vs_pinned_baseline"] = round(raw, 2)
        out["pinned_host_rate"] = rec["host_rate"]
        out["vs_baseline"] = out["vs_pinned_baseline"]
    else:
        raw = same_run_ratio
        out["vs_baseline"] = round(same_run_ratio, 2)
    # full-precision ratio for aggregation (geomeans must not
    # accumulate display rounding); underscore = not a record field
    out["_ratio_raw"] = raw
    return out


# Measured spread of tunnel round-trip jitter on this host (single source of
# truth — benchmarks/suite.py imports it): a marginal per-fold time below
# TUNNEL_JITTER_S / chain is noise, not device time.
TUNNEL_JITTER_S = 40e-3

# TPU v5e HBM peak (public spec): the roofline every marginal is checked
# against.  A fold whose bytes-touched lower bound divided by its measured
# marginal exceeds this rate is IMPOSSIBLE — the chain was hoisted/elided —
# and the measurement is rejected (the round-1 hoisting bug, mechanized).
HBM_PEAK_GBPS = 819.0


def orset_fold_bytes_model(N: int, E: int, R: int) -> int:
    """Bytes ANY implementation of the dense ORSet fold must touch:
    read + write both (E, R) planes, the op columns, the clock."""
    return 2 * (2 * E * R * 4) + 13 * N + 2 * 4 * R


def roofline_pct(bytes_model: float, t_dev: float, on_tpu: bool):
    """% of v5e HBM peak implied by touching ``bytes_model`` bytes in
    ``t_dev`` seconds; None off-TPU (the constant is the TPU's)."""
    if not on_tpu or t_dev <= 0:
        return None
    return round(100.0 * bytes_model / t_dev / (HBM_PEAK_GBPS * 1e9), 1)


def force_completion(out):
    """``block_until_ready`` alone can return before the tunneled TPU has
    materialized results; pulling one scalar to host forces it."""
    import jax

    leaf = jax.tree_util.tree_leaves(out)[0]
    np.asarray(leaf).ravel()[:1]


def gen_columns(N: int, R: int, E: int, seed: int = 7):
    """Vectorized op-stream generator: per-actor sequential add dots,
    ~10% removes whose horizon is the actor's add-count so far."""
    rng = np.random.default_rng(seed)
    kind = (rng.random(N) < 0.10).astype(np.int8)
    member = rng.integers(0, E, N, dtype=np.int32)
    actor = rng.integers(0, R, N, dtype=np.int32)
    is_add = kind == 0
    # per-actor running count of adds, in row order (stable sort trick)
    order = np.argsort(actor, kind="stable")
    s_actor = actor[order]
    s_isadd = is_add[order].astype(np.int64)
    cum = np.cumsum(s_isadd)
    starts = np.searchsorted(s_actor, np.arange(R))
    base = np.where(starts < N, cum[np.minimum(starts, N - 1)] - s_isadd[np.minimum(starts, N - 1)], 0)
    within = cum - base[s_actor]
    counter = np.empty(N, np.int64)
    counter[order] = within
    counter = counter.astype(np.int32)
    # removes before the actor ever added → sentinel padding rows
    dead_rm = (~is_add) & (counter == 0)
    actor = np.where(dead_rm, R, actor)
    return kind, member, actor, counter


def host_fold(kind, member, actor, counter, R: int):
    """Single-core baseline: the host-reference ORSet applied op-by-op."""
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.models.orset import AddOp, RmOp
    from crdt_enc_tpu.models.vclock import Dot, VClock

    state = ORSet()
    t0 = time.perf_counter()
    for k, m, a, c in zip(kind.tolist(), member.tolist(), actor.tolist(), counter.tolist()):
        if a >= R:
            continue
        if k == 0:
            state.apply(AddOp(m, Dot(a, c)))
        else:
            state.apply(RmOp(m, VClock({a: c})))
    return state, time.perf_counter() - t0


def _producers_arg() -> list:
    """The ``--producers`` sweep list: a comma-separated count list after
    the flag (e.g. ``--producers 1,2,4``), a single N, or [1] when the
    flag is absent (the historical single-producer pipeline)."""
    if "--producers" in sys.argv:
        i = sys.argv.index("--producers")
        if i + 1 < len(sys.argv):
            try:
                ns = [int(x) for x in sys.argv[i + 1].split(",") if x.strip()]
            except ValueError:
                raise SystemExit(
                    f"--producers wants N or N,N,... got {sys.argv[i + 1]!r}"
                )
            if ns and all(n > 0 for n in ns):
                return ns
        raise SystemExit("--producers wants a positive count list")
    return [1]


def e2e_streaming(smoke: bool):
    """BASELINE config #5 END-TO-END: encrypted op-file blobs in →
    byte-identical compacted OR-Set state out, measuring the overlapped
    streaming-compaction pipeline (ops/stream.py; N producer threads run
    threaded native decrypt + decode for upcoming chunks while the
    consumer columnarizes and folds the current one, a sequencer keeping
    chunk order deterministic) against the NON-overlapped
    single-dispatch front end (every stage sequential) on the identical
    workload.  ``--producers 1,2,4`` sweeps the fan-out width; every N
    is byte-equality-checked against the sequential state and records
    its marginal + obs snapshot.  Prints one JSON line and appends the
    full record — with the per-stage marginals from the trace spans and
    the per-N sweep table — to BENCH_LOCAL.jsonl.

    Env knobs: BENCH_E2E_OPS (200_000), BENCH_E2E_REPLICAS (100_000),
    BENCH_E2E_MEMBERS (1024), BENCH_E2E_OPF (48, ops per file),
    BENCH_E2E_CHUNKS (8), BENCH_E2E_ITERS (3).
    """
    import secrets

    N = int(os.environ.get("BENCH_E2E_OPS", 10_000 if smoke else 200_000))
    R = int(os.environ.get("BENCH_E2E_REPLICAS", 500 if smoke else 100_000))
    E = int(os.environ.get("BENCH_E2E_MEMBERS", 128 if smoke else 1024))
    OPF = int(os.environ.get("BENCH_E2E_OPF", 48))
    N_CHUNKS = int(os.environ.get("BENCH_E2E_CHUNKS", 8))
    ITERS = int(os.environ.get("BENCH_E2E_ITERS", 3))

    platforms = os.environ.get("JAX_PLATFORMS", "").lower()
    first_platform = platforms.split(",")[0].strip() if platforms else ""
    want_tpu = first_platform not in ("cpu",) and not smoke
    jax, dev = acquire_jax(want_tpu)

    import crdt_enc_tpu
    from benchmarks.suite import _build_encrypted_files
    from crdt_enc_tpu.backends.xchacha import decrypt_blobs_packed
    from crdt_enc_tpu.models import ORSet
    from crdt_enc_tpu.parallel import TpuAccelerator
    from crdt_enc_tpu.utils import codec, trace

    crdt_enc_tpu.enable_compilation_cache()
    key = secrets.token_bytes(32)
    payloads, plain, _headers, actors = _build_encrypted_files(
        N, R, E, OPF, key, n_headers=0
    )
    total_ops = sum(len(codec.unpack(p)) for p in plain)
    accel = TpuAccelerator()
    actors_sorted = sorted(actors)
    log(
        f"e2e_streaming: device {dev.platform}; {len(payloads)} files, "
        f"{total_ops} ops, R={R} E={E}"
    )

    # ---- non-overlapped single-dispatch front end: every stage runs to
    # completion before the next starts (ONE decrypt batch, then decode,
    # then fold+writeback) — the exact serial sum the pipeline hides
    def sequential():
        state = ORSet()
        session = accel.open_fold_session(state, actors_hint=actors_sorted)
        packed = decrypt_blobs_packed(key, payloads)
        session.reduce_chunk(session.decode_chunk(packed))
        session.finish()
        return state

    # ---- overlapped pipeline (the product path, accel front door),
    # swept over the --producers fan-out widths
    producer_list = _producers_arg()

    def overlapped(n_producers: int):
        state = ORSet()
        ok = accel.fold_encrypted_stream(
            state, key, payloads, actors_hint=actors_sorted,
            n_chunks=N_CHUNKS, n_producers=n_producers,
        )
        assert ok, "accelerator declined the streaming fold"
        return state

    seq_state = sequential()  # warmup + compile + equality witness
    seq_bytes = codec.pack(seq_state.to_obj())

    t_seq = min(_timed_host(sequential) for _ in range(ITERS))
    # per-N: byte equality vs the sequential state, then the best-of-ITERS
    # wall with the per-stage marginals + full obs snapshot (stage
    # histograms with p50/p95/p99, recompile + transfer counters,
    # device-memory gauges) of the best pass.  The accelerator wired
    # jax_compiles tracking at construction (obs.runtime); a non-zero
    # count on a post-warmup pass is the ADVICE-r5 recompile bug class.
    sweep = {}
    raw_times = {}  # unrounded best wall per N — ratios use these
    full_batch_equal = True
    for n_prod in producer_list:
        ovl_state = overlapped(n_prod)  # warmup + equality witness
        equal = codec.pack(ovl_state.to_obj()) == seq_bytes
        full_batch_equal = full_batch_equal and equal
        log(f"overlapped[N={n_prod}] ≡ sequential (full batch): {equal}")
        t_best = float("inf")
        obs_snapshot = {}
        stage_marginals = {}
        for _ in range(ITERS):
            trace.reset()
            t = _timed_host(lambda: overlapped(n_prod))
            if t < t_best:
                t_best = t
                obs_snapshot = trace.snapshot()
                stage_marginals = {
                    name: round(v["seconds"], 4)
                    for name, v in obs_snapshot["spans"].items()
                    if name.startswith(("stream.", "session."))
                }
        trace.reset()
        raw_times[str(n_prod)] = t_best
        sweep[str(n_prod)] = {
            "e2e_s": round(t_best, 4),
            "ops_per_sec": round(total_ops / t_best, 1),
            "speedup_vs_sequential": round(t_seq / t_best, 2),
            "full_batch_equal": bool(equal),
            "stage_marginals_s": stage_marginals,
            "obs": obs_snapshot,
        }
        log(
            f"e2e[N={n_prod}]: overlapped {t_best:.3f}s "
            f"({total_ops / t_best:,.0f} ops/s) vs sequential {t_seq:.3f}s "
            f"→ {t_seq / t_best:.2f}x overlap win"
        )
    best_n = min(raw_times, key=raw_times.get)
    t_ovl = raw_times[best_n]  # unrounded — display rounding must not
    rate = total_ops / t_ovl   # leak into the recorded rate/ratios
    # machine-checked critical-path attribution of the best pass: the
    # ROADMAP-item-1 "where did the time go" claim as a number with a
    # trend trajectory (obs.attribution; render with `obs_report gap`)
    from crdt_enc_tpu.obs import attribution

    gap_report = attribution.attribute_cycle(
        sweep[best_n]["obs"], pipeline="streaming", wall_s=t_ovl,
        ops=total_ops,
    )
    if not full_batch_equal:
        # byte divergence from the sequential scalar path: the number is
        # meaningless and a record would poison the trend ratchet —
        # refuse loudly (same contract as --e2e-delta/--e2e-multitenant)
        log("REFUSING to record: overlapped state diverged from sequential")
        raise SystemExit(1)
    result = {
        "metric": "orset_e2e_streaming_ops_per_sec",
        "config": "mixed_streaming_100k_e2e",
        "value": round(rate, 1),
        "unit": "ops/s",
        "e2e_overlapped_s": round(t_ovl, 4),
        "e2e_sequential_s": round(t_seq, 4),
        "overlap_speedup": sweep[best_n]["speedup_vs_sequential"],
        "producers_best": int(best_n),
        # per-N marginal table WITHOUT the obs payloads (those go in the
        # full BENCH_LOCAL record below) — stdout stays one short line
        "producer_sweep": {
            n: {k: v for k, v in rec.items() if k != "obs"}
            for n, rec in sweep.items()
        },
        "stage_marginals_s": sweep[best_n]["stage_marginals_s"],
        "gap_report": gap_report,
        "full_batch_equal": bool(full_batch_equal),
        "backend": dev.platform,
    }
    if "1" in raw_times and best_n != "1":
        result["producer_speedup_vs_1"] = round(
            raw_times["1"] / t_ovl, 2
        )
    print(json.dumps(result))
    if os.environ.get("BENCH_LOCAL_DISABLE") == "1":
        return
    if dev.platform != "tpu" and os.environ.get("BENCH_LOCAL_ALL") != "1":
        return
    _append_local({
        **result,
        "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"),
        "device_kind": dev.device_kind,
        # host_cpus contextualizes the overlap number: with ≤2 cores the
        # producers, the consumer, and the decrypt pool share the same
        # silicon, so fan-out cannot beat the serial sum — the win
        # needs a device fold or idle host cores (the TPU configuration)
        "host_cpus": os.cpu_count(),
        "shape": {"N": N, "R": R, "E": E, "ops_per_file": OPF,
                  "files": len(payloads), "n_chunks": N_CHUNKS,
                  "total_ops": total_ops},
        # full per-N registry snapshots: per-stage histograms
        # (p50/p95/p99/max), jax_compiles / h2d_bytes counters, device
        # memory gauges, the stream_producers gauge — render with
        # `python -m crdt_enc_tpu.tools.obs_report report BENCH_LOCAL.jsonl`
        "producer_sweep_obs": {n: rec["obs"] for n, rec in sweep.items()},
        "obs": sweep[best_n]["obs"],
    })


def device_decode_exp(smoke: bool):
    """The CRDT_DEVICE_DECODE experiment, measured honestly (ISSUE 13
    layer 4): decode the fixed-stride add-op framing (a) on device
    (jnp strided gathers after bulk AEAD, ops/device_decode.py), (b)
    with the same vectorized extraction on host numpy (the control arm
    — isolates WHERE the gather runs), and (c) through the production
    native C decoder (the incumbent).  All three must produce identical
    columns; the record carries all three walls and names the winner.
    Runs on an ALL-ADDS corpus — the device kernel's best case by
    construction; mixed corpora fall back to (c) in production.

    Env knobs: BENCH_DD_OPS (200_000), BENCH_DD_REPLICAS (100_000),
    BENCH_DD_OPF (48), BENCH_DD_ITERS (5).
    """
    import secrets

    N = int(os.environ.get("BENCH_DD_OPS", 10_000 if smoke else 200_000))
    R = int(os.environ.get("BENCH_DD_REPLICAS", 500 if smoke else 100_000))
    OPF = int(os.environ.get("BENCH_DD_OPF", 48))
    ITERS = int(os.environ.get("BENCH_DD_ITERS", 5))
    platforms = os.environ.get("JAX_PLATFORMS", "").lower()
    first_platform = platforms.split(",")[0].strip() if platforms else ""
    want_tpu = first_platform not in ("cpu",) and not smoke
    jax, dev = acquire_jax(want_tpu)

    import numpy as np

    from crdt_enc_tpu.ops.device_decode import (
        decode_adds_device, decode_adds_host,
    )
    from crdt_enc_tpu.ops.native_decode import decode_orset_payload_batch
    from crdt_enc_tpu.utils import codec

    rng = np.random.default_rng(7)
    actors = sorted(secrets.token_bytes(16) for _ in range(R))
    payloads = []
    for lo in range(0, N, OPF):
        ops = [
            [0, int(rng.integers(0, 128)),
             [actors[int(rng.integers(0, R))], int(rng.integers(1, 128))]]
            for _ in range(min(OPF, N - lo))
        ]
        payloads.append(codec.pack(ops))
    lens = np.array([len(p) for p in payloads], np.uint64)
    offs = np.zeros(len(payloads) + 1, np.uint64)
    np.cumsum(lens, out=offs[1:])
    buf = np.frombuffer(b"".join(payloads), np.uint8)
    packed = (buf, offs)
    log(
        f"device_decode: device {dev.platform}; {len(payloads)} payloads, "
        f"{N} add ops, R={R}"
    )

    dd = decode_adds_device(packed, actors)
    assert dd is not None, "all-adds corpus must qualify for the device path"
    hh = decode_adds_host(packed, actors)
    nn = decode_orset_payload_batch(list(payloads), actors)
    # identical columns across all three arms — refuse to record otherwise
    for name, got in (("host_vectorized", hh), ("native", nn)):
        assert got is not None, name
        k2, m2, a2, c2 = got[0], got[1], got[2], got[3]
        mobj = got[4]
        assert (np.asarray(k2) == np.asarray(dd[0])).all(), name
        assert (np.asarray(a2) == np.asarray(dd[2])).all(), name
        assert (np.asarray(c2) == np.asarray(dd[3])).all(), name
        # member identity via resolved objects (intern order differs)
        got_members = [mobj[int(i)] for i in np.asarray(m2)[:64].tolist()]
        dd_members = [dd[4][int(i)] for i in np.asarray(dd[1])[:64].tolist()]
        assert got_members == dd_members, name

    def best(fn):
        t = float("inf")
        for _ in range(ITERS):
            t0 = time.perf_counter()
            r = fn()
            assert r is not None  # arms validated identical above
            t = min(t, time.perf_counter() - t0)
        return t

    t_dev = best(lambda: decode_adds_device(packed, actors))
    t_host = best(lambda: decode_adds_host(packed, actors))
    t_native = best(lambda: decode_orset_payload_batch(list(payloads), actors))
    arms = {"device": t_dev, "host_vectorized": t_host, "native": t_native}
    winner = min(arms, key=arms.get)
    result = {
        "metric": "orset_device_decode_ops_per_sec",
        "config": f"device_decode_adds_{N // 1000}k",
        "value": round(N / arms[winner], 1),
        "unit": "ops/s",
        "winner": winner,
        "arms_s": {k: round(v, 5) for k, v in arms.items()},
        "device_vs_native_x": round(t_dev / t_native, 2),
        "shape": {"N": N, "R": R, "ops_per_file": OPF,
                  "files": len(payloads)},
        "backend": dev.platform,
    }
    print(json.dumps(result))
    if os.environ.get("BENCH_LOCAL_DISABLE") == "1":
        return
    if dev.platform != "tpu" and os.environ.get("BENCH_LOCAL_ALL") != "1":
        return
    _append_local({
        **result,
        "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"),
        "device_kind": dev.device_kind,
        "host_cpus": os.cpu_count(),
    })


def _timed_host(fn):
    """Wall-clock one end-to-end pass (host stages dominate; there is no
    tunnel-marginal trick to play — the honest number is the wall)."""
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def _mesh_arg():
    """``--mesh dp=N[,mp=M]`` → ``(dp, mp)`` for the sharded-service
    arm of the multitenant sweep, else None (single-chip only)."""
    if "--mesh" not in sys.argv:
        return None
    i = sys.argv.index("--mesh")
    if i + 1 >= len(sys.argv):
        raise SystemExit("--mesh wants dp=N[,mp=M]")
    spec = sys.argv[i + 1]
    from crdt_enc_tpu.parallel.mesh import parse_mesh_spec

    try:
        return parse_mesh_spec(spec)
    except ValueError as e:
        raise SystemExit(f"--mesh: {e} (got {spec!r})")


def _tenants_arg(default: int) -> int:
    """``--tenants N`` (the multitenant sweep size), else ``default``."""
    if "--tenants" in sys.argv:
        i = sys.argv.index("--tenants")
        if i + 1 < len(sys.argv):
            try:
                n = int(sys.argv[i + 1])
            except ValueError:
                raise SystemExit(f"--tenants wants N, got {sys.argv[i + 1]!r}")
            if n > 0:
                return n
        raise SystemExit("--tenants wants a positive count")
    return default


def _nearest_rank(vals: list, frac: float):
    """THE nearest-rank quantile (ceil(frac·n)-th smallest) — one
    implementation for every bench family, so p99 can never silently
    mean different things across records."""
    import math

    s = sorted(vals)
    return s[min(len(s) - 1, max(0, math.ceil(frac * len(s)) - 1))]


def _quantiles_ms(samples_s: list) -> dict:
    """Exact nearest-rank p50/p99 of a latency sample set, in ms (the
    obs histograms are ±9% bucketed; the bench records exact values)."""
    return {
        "p50_ms": round(_nearest_rank(samples_s, 0.50) * 1e3, 2),
        "p99_ms": round(_nearest_rank(samples_s, 0.99) * 1e3, 2),
        "max_ms": round(max(samples_s) * 1e3, 2),
    }


def e2e_multitenant(smoke: bool):
    """ISSUE-7 acceptance: the multi-tenant fold service
    (crdt_enc_tpu/serve/) vs sequential per-tenant solo compacts.

    T tenants, each its own encrypted remote (memory backend, XChaCha
    AEAD, three-layer wire format) populated with a config-3-shaped op
    stream across a few replica actors.  The remotes are duplicated;
    one copy is compacted tenant-by-tenant through the normal solo
    ``Core.compact()`` loop, the other through ONE
    ``FoldService.run_cycle()`` — ragged-bucketed mega-folds, shared
    decode fan-out, per-tenant sealed snapshots.  Byte equality of
    every tenant's state is ASSERTED (the run refuses to record
    otherwise); the headline is *aggregate* ops/s and the p50/p99
    per-tenant completion latency (sequential tenants queue behind each
    other — that IS the serving model being replaced).  A second
    service cycle over a ~10% op tail measures the warm-tier path
    (plane reuse across cycles).  Appends the full record + obs
    snapshot to BENCH_LOCAL.jsonl (CPU records need BENCH_LOCAL_ALL=1,
    as for the other e2e benches).

    The default shape is the many-SMALL-tenants fleet the serving layer
    exists for: 384 ops per tenant flushed as 24-op files (16 pending
    files), where a solo compact's cost is machinery-bound (the
    pipelined ingest engages at 16 files and costs ~7-8ms/tenant on
    this box almost independent of op count) — exactly the per-tenant
    overhead the batch amortizes.  Bigger tenants shift the balance
    toward shared work (decrypt/decode/fold) that both sides pay;
    sweep BENCH_MT_OPS/BENCH_MT_OPF to map the landscape.

    Env knobs: BENCH_MT_TENANTS (256; --tenants N overrides),
    BENCH_MT_OPS (384 per tenant), BENCH_MT_REPLICAS (4 per tenant),
    BENCH_MT_MEMBERS (64 per tenant), BENCH_MT_OPF (24 ops/file),
    BENCH_MT_TAIL_PCT (10), BENCH_MT_ITERS (3 — best-of passes per
    side, each on fresh fleet copies).

    ``--mesh dp=N[,mp=M]`` adds the SHARDED arm (ISSUE 14): the same
    fleet through a mesh-backed FoldService — tenant lanes over dp,
    member planes over mp — byte-compared against both other arms and
    recorded under its own metric/config with per-arm steady-state
    compile counts.  On a CPU box the virtual mesh
    (XLA_FLAGS=--xla_force_host_platform_device_count=8) exercises the
    exact SPMD programs a pod would run, but all "devices" share the
    host's cores — the CPU record is a correctness + compile-count
    witness, not a speedup claim (that awaits TPU hardware, the PR-7
    caveat verbatim).
    """
    import asyncio
    import copy

    T = _tenants_arg(int(os.environ.get(
        "BENCH_MT_TENANTS", 16 if smoke else 256)))
    N = int(os.environ.get("BENCH_MT_OPS", 96 if smoke else 384))
    R = int(os.environ.get("BENCH_MT_REPLICAS", 4))
    E = int(os.environ.get("BENCH_MT_MEMBERS", 64))
    OPF = int(os.environ.get("BENCH_MT_OPF", 24))
    TAIL_PCT = float(os.environ.get("BENCH_MT_TAIL_PCT", 10.0))

    platforms = os.environ.get("JAX_PLATFORMS", "").lower()
    first_platform = platforms.split(",")[0].strip() if platforms else ""
    want_tpu = first_platform not in ("cpu",) and not smoke
    jax, dev = acquire_jax(want_tpu)

    import crdt_enc_tpu
    from benchmarks.suite import actor_bytes_table
    from crdt_enc_tpu.backends import (
        MemoryRemote, MemoryStorage, PlainKeyCryptor, XChaChaCryptor,
    )
    from crdt_enc_tpu.core import Core, OpenOptions, orset_adapter
    from crdt_enc_tpu.models import canonical_bytes
    from crdt_enc_tpu.parallel import TpuAccelerator
    from crdt_enc_tpu.serve import FoldService
    from crdt_enc_tpu.utils import trace
    from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

    crdt_enc_tpu.enable_compilation_cache()

    # --mesh dp=N[,mp=M]: a third arm runs the SAME fleet through a
    # mesh-backed FoldService (tenant lanes over dp, member planes over
    # mp — parallel/mesh.py), byte-compared against both other arms
    mesh_shape = _mesh_arg()
    mesh = None
    if mesh_shape is not None:
        dp_m, mp_m = mesh_shape
        if len(jax.devices()) < dp_m * mp_m:
            raise SystemExit(
                f"--mesh dp={dp_m},mp={mp_m} needs {dp_m * mp_m} devices, "
                f"found {len(jax.devices())}; on a CPU box set XLA_FLAGS="
                "--xla_force_host_platform_device_count=8 (the virtual "
                "mesh the tier-1 differential tests use)"
            )
        from crdt_enc_tpu.parallel.mesh import make_mesh

        mesh = make_mesh((dp_m, mp_m))

    def opts(storage):
        return OpenOptions(
            storage=storage,
            cryptor=XChaChaCryptor(),
            key_cryptor=PlainKeyCryptor(),
            adapter=orset_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=True,
            accelerator=TpuAccelerator(),
        )

    actors = actor_bytes_table(R)

    def tenant_files(seed: int):
        """One tenant's op-file payload stream (config-3-shaped adds +
        removes over R actors, OPF ops/file, dense versions per actor)."""
        kind, member, actor, counter = gen_columns(N, R, E, seed=seed)
        live = actor < R
        order = np.argsort(actor[live], kind="stable")
        k_l, m_l = kind[live][order], member[live][order]
        a_l, c_l = actor[live][order], counter[live][order]
        i, n = 0, len(k_l)
        versions: dict = {}
        out = []
        while i < n:
            j = min(i + OPF, n)
            j = i + int(np.searchsorted(a_l[i:j], a_l[i], side="right"))
            ab = actors[int(a_l[i])]
            ops = []
            for t in range(i, j):
                if k_l[t] == 0:
                    ops.append([0, int(m_l[t]), [ab, int(c_l[t])]])
                else:
                    ops.append([1, int(m_l[t]), {ab: int(c_l[t])}])
            v = versions.get(ab, 0) + 1
            versions[ab] = v
            out.append((ab, v, ops))
            i = j
        return out

    async def build():
        """Per tenant: a pristine remote of sealed head files, plus the
        tail PRE-SEALED as raw blobs (so the warm-cycle phase can drop
        them into any fleet copy's storage)."""
        remotes, tails, total_ops = [], [], 0
        for t in range(T):
            files = tenant_files(seed=100 + t)
            n_tail = max(1, int(len(files) * TAIL_PCT / 100.0))
            head, tail = files[:-n_tail], files[-n_tail:]
            remote = MemoryRemote()
            writer = await Core.open(opts(MemoryStorage(remote)))
            for ab, v, ops in head:
                blob = await writer._seal(ops)
                await writer.storage.store_ops(ab, v, blob)
            total_ops += sum(len(ops) for _, _, ops in head)
            remotes.append(remote)
            tails.append([
                (ab, v, await writer._seal(ops), len(ops))
                for ab, v, ops in tail
            ])
        return remotes, tails, total_ops

    remotes, tails, total_ops = asyncio.run(build())
    log(
        f"e2e_multitenant: device {dev.platform}; {T} tenants, "
        f"{total_ops} head ops total, R={R}/tenant E={E}/tenant"
    )

    ITERS = max(1, int(os.environ.get("BENCH_MT_ITERS", 1 if smoke else 3)))

    async def measure():
        # ---- warmup: compile exclusion, the repo's standard protocol.
        # A throwaway copy of the fleet runs one full service cycle (the
        # mega-fold compiles per size class, T included) and a few solo
        # compacts (the session fold's buckets) — the measured passes
        # below are steady-state on both sides.
        warm_fleet = [
            await Core.open(opts(MemoryStorage(copy.deepcopy(r))))
            for r in remotes
        ]
        await FoldService(warm_fleet).run_cycle()
        for r in remotes[: min(8, T)]:
            c = await Core.open(opts(MemoryStorage(copy.deepcopy(r))))
            await c.compact()
        del warm_fleet
        if mesh is not None:  # compile the sharded bucket classes too
            mesh_warm = [
                await Core.open(opts(MemoryStorage(copy.deepcopy(r))))
                for r in remotes
            ]
            await FoldService(mesh_warm, mesh=mesh).run_cycle()
            del mesh_warm

        # ---- best-of-ITERS passes (each on fresh fleet copies, byte
        # equality asserted on EVERY pair — the e2e-streaming protocol:
        # wall minima, with the full sample sets recorded)
        t_seq = t_serve = t_shard = float("inf")
        seq_lat = serve_lat = shard_lat = None
        obs_seq = obs_serve = obs_shard = None
        equal = True
        paths: dict = {}
        shard_paths: dict = {}
        service = None
        for _ in range(ITERS):
            solo_cores = [
                await Core.open(opts(MemoryStorage(copy.deepcopy(r))))
                for r in remotes
            ]
            served_cores = [
                await Core.open(opts(MemoryStorage(copy.deepcopy(r))))
                for r in remotes
            ]
            # sequential baseline: tenant-by-tenant solo compacts; a
            # tenant's completion latency includes its queue wait — that
            # is the one-remote-at-a-time serving model being replaced
            trace.reset()
            lat = []
            t0 = time.perf_counter()
            for c in solo_cores:
                await c.compact()
                lat.append(time.perf_counter() - t0)
            t = time.perf_counter() - t0
            if t < t_seq:
                t_seq, seq_lat, obs_seq = t, lat, trace.snapshot()

            # one service cycle over the whole fleet
            svc = FoldService(served_cores)
            trace.reset()
            t0 = time.perf_counter()
            results = await svc.run_cycle()
            t = time.perf_counter() - t0
            errors = [
                (i, r.error) for i, r in enumerate(results) if r.error
            ]
            assert not errors, f"service tenant errors: {errors[:3]}"
            equal = equal and all(
                a.with_state(canonical_bytes)
                == b.with_state(canonical_bytes)
                for a, b in zip(solo_cores, served_cores)
            )
            if t < t_serve:
                t_serve = t
                serve_lat = [r.latency_s for r in results]
                obs_serve = trace.snapshot()
                paths = {}
                for r in results:
                    paths[r.path] = paths.get(r.path, 0) + 1
                service = svc
                warm_fleet_cores = served_cores

            if mesh is not None:
                # sharded arm: one mesh-backed cycle on a third fresh
                # fleet copy, byte-compared against the solo arm (the
                # record REFUSES on any per-tenant divergence)
                shard_cores = [
                    await Core.open(opts(MemoryStorage(copy.deepcopy(r))))
                    for r in remotes
                ]
                svc_m = FoldService(shard_cores, mesh=mesh)
                trace.reset()
                t0 = time.perf_counter()
                results_m = await svc_m.run_cycle()
                t = time.perf_counter() - t0
                errors = [
                    (i, r.error) for i, r in enumerate(results_m) if r.error
                ]
                assert not errors, f"sharded tenant errors: {errors[:3]}"
                equal = equal and all(
                    a.with_state(canonical_bytes)
                    == b.with_state(canonical_bytes)
                    for a, b in zip(solo_cores, shard_cores)
                )
                if t < t_shard:
                    t_shard = t
                    shard_lat = [r.latency_s for r in results_m]
                    obs_shard = trace.snapshot()
                    shard_paths = {}
                    for r in results_m:
                        shard_paths[r.path] = shard_paths.get(r.path, 0) + 1

        # ---- warm cycle: the TAIL_PCT op tail lands on the best pass's
        # fleet, the service folds it through the warm plane tier
        n_tail_ops = 0
        for core, tail in zip(warm_fleet_cores, tails):
            for ab, v, blob, n_ops in tail:
                await core.storage.store_ops(ab, v, blob)
                n_tail_ops += n_ops
        trace.reset()
        t0 = time.perf_counter()
        results2 = await service.run_cycle()
        t_warm = time.perf_counter() - t0
        snap2 = trace.snapshot()
        warm_hits = snap2["counters"].get("serve_warm_hits", 0)
        assert all(r.error is None for r in results2)

        return (
            t_seq, t_serve, seq_lat, serve_lat, equal, paths, obs_seq,
            obs_serve, t_warm, n_tail_ops, warm_hits,
            t_shard, shard_lat, obs_shard, shard_paths,
        )

    (t_seq, t_serve, seq_lat, serve_lat, equal, paths, obs_seq, obs_serve,
     t_warm, n_tail_ops, warm_hits,
     t_shard, shard_lat, obs_shard, shard_paths) = asyncio.run(measure())

    agg_serve = total_ops / t_serve
    agg_seq = total_ops / t_seq
    speedup = t_seq / t_serve
    # critical-path attribution of the best service cycle (obs
    # .attribution; the serve twin of the streaming gap report)
    from crdt_enc_tpu.obs import attribution

    gap_report = attribution.attribute_cycle(
        obs_serve, pipeline="serve", wall_s=t_serve, ops=total_ops
    )
    log(
        f"sequential {t_seq:.2f}s ({agg_seq:,.0f} ops/s) vs service "
        f"{t_serve:.2f}s ({agg_serve:,.0f} ops/s) → {speedup:.2f}x; "
        f"byte-identical: {equal}; paths: {paths}"
    )
    log(
        f"warm cycle: {n_tail_ops} tail ops in {t_warm:.2f}s "
        f"({n_tail_ops / t_warm:,.0f} ops/s, warm hits {warm_hits}/{T})"
    )
    compiles = lambda snap: int(
        (snap or {}).get("counters", {}).get("jax_compiles", 0)
    )
    sharded_rec = None
    if mesh is not None:
        agg_shard = total_ops / t_shard
        log(
            f"sharded (dp={dp_m},mp={mp_m}): {t_shard:.2f}s "
            f"({agg_shard:,.0f} ops/s) = {t_serve / t_shard:.2f}x vs "
            f"single-chip service; paths: {shard_paths}; steady-state "
            f"compiles seq/service/sharded = {compiles(obs_seq)}/"
            f"{compiles(obs_serve)}/{compiles(obs_shard)}"
        )
        sharded_rec = {
            "mesh": {"dp": dp_m, "mp": mp_m},
            "cycle_s": round(t_shard, 4),
            "agg_ops_per_sec": round(agg_shard, 1),
            "vs_single_chip": round(t_serve / t_shard, 2),
            "tenant_latency": _quantiles_ms(shard_lat),
            "fold_paths": shard_paths,
        }
    result = {
        "metric": "orset_multitenant_agg_ops_per_sec",
        "config": f"multitenant_{T}t",
        "value": round(agg_serve, 1),
        "unit": "ops/s",
        "vs_baseline": round(speedup, 2),
        "sequential_agg_ops_per_sec": round(agg_seq, 1),
        "service_cycle_s": round(t_serve, 4),
        "sequential_s": round(t_seq, 4),
        "tenant_latency": _quantiles_ms(serve_lat),
        "sequential_tenant_latency": _quantiles_ms(seq_lat),
        "fold_paths": paths,
        "gap_report": gap_report,
        "warm_cycle": {
            "tail_ops": n_tail_ops,
            "cycle_s": round(t_warm, 4),
            "ops_per_sec": round(n_tail_ops / t_warm, 1),
            "warm_hits": warm_hits,
        },
        "byte_identical": bool(equal),
        "backend": dev.platform,
        # steady-state XLA compiles in the measured passes (post-warmup
        # — zero is the bucket quantization contract, mesh included)
        "compile_counts": {
            "sequential": compiles(obs_seq),
            "service": compiles(obs_serve),
            **({"sharded": compiles(obs_shard)} if mesh is not None else {}),
        },
    }
    if sharded_rec is not None:
        # its own metric/config so the trend gate tracks the sharded
        # trajectory separately from the single-chip one
        result["metric"] = "orset_multitenant_sharded_agg_ops_per_sec"
        result["config"] = f"multitenant_{T}t_mesh{dp_m}x{mp_m}"
        result["value"] = sharded_rec["agg_ops_per_sec"]
        result["sharded"] = sharded_rec
        result["single_chip_agg_ops_per_sec"] = round(agg_serve, 1)
    print(json.dumps(result))
    if not equal:
        log("FAILED: per-tenant states diverged — refusing to record")
        raise SystemExit(1)
    if os.environ.get("BENCH_LOCAL_DISABLE") == "1":
        return
    if dev.platform != "tpu" and os.environ.get("BENCH_LOCAL_ALL") != "1":
        return
    _append_local({
        **result,
        "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"),
        "device_kind": dev.device_kind,
        # with 2 cores the decode fan-out and the consumer share
        # silicon; the dispatch-amortization win is what remains —
        # large-tenant-count and TPU numbers await hardware (same
        # caveat as the PR-1/PR-3 records)
        "host_cpus": os.cpu_count(),
        "shape": {"tenants": T, "ops_per_tenant": N, "replicas": R,
                  "members": E, "ops_per_file": OPF,
                  "total_ops": total_ops, "iters": ITERS},
        "obs": obs_serve,
        "obs_sequential": obs_seq,
        **({"obs_sharded": obs_shard} if mesh is not None else {}),
    })


def _daemon_fleet_shape(smoke: bool):
    """The --e2e-daemon workload shape (env knobs BENCH_DMN_*): T
    single-remote tenants of N config-3-shaped ops in OPF-op encrypted
    files — the many-small-tenants fleet of docs/multitenant.md, plus a
    churn script (joiners, leavers, bursters) sized off T."""
    T = _tenants_arg(int(os.environ.get(
        "BENCH_DMN_TENANTS", 16 if smoke else 256)))
    N = int(os.environ.get("BENCH_DMN_OPS", 96 if smoke else 256))
    R = int(os.environ.get("BENCH_DMN_REPLICAS", 4))
    E = int(os.environ.get("BENCH_DMN_MEMBERS", 64))
    OPF = int(os.environ.get("BENCH_DMN_OPF", 24))
    CYCLES = int(os.environ.get("BENCH_DMN_CYCLES", 4 if smoke else 6))
    return T, N, R, E, OPF, CYCLES


def _daemon_tenant_files(N, R, E, OPF, seed):
    """One tenant's (actor, version, ops) file stream — the
    e2e-multitenant generator shape, shared by the daemon bench and its
    pinned host baseline."""
    from benchmarks.suite import actor_bytes_table

    actors = actor_bytes_table(R)
    kind, member, actor, counter = gen_columns(N, R, E, seed=seed)
    live = actor < R
    order = np.argsort(actor[live], kind="stable")
    k_l, m_l = kind[live][order], member[live][order]
    a_l, c_l = actor[live][order], counter[live][order]
    i, n = 0, len(k_l)
    versions: dict = {}
    out = []
    while i < n:
        j = min(i + OPF, n)
        j = i + int(np.searchsorted(a_l[i:j], a_l[i], side="right"))
        ab = actors[int(a_l[i])]
        ops = []
        for t in range(i, j):
            if k_l[t] == 0:
                ops.append([0, int(m_l[t]), [ab, int(c_l[t])]])
            else:
                ops.append([1, int(m_l[t]), {ab: int(c_l[t])}])
        v = versions.get(ab, 0) + 1
        versions[ab] = v
        out.append((ab, v, ops))
        i = j
    return out


async def _daemon_build_remotes(opts_fn, n_tenants, N, R, E, OPF, seed0):
    """``n_tenants`` pristine encrypted remotes + per-tenant head op
    counts; burst tails are returned PRE-SEALED so churn can drop them
    into a live tenant's storage mid-run."""
    import math

    from crdt_enc_tpu.backends import MemoryRemote, MemoryStorage
    from crdt_enc_tpu.core import Core

    remotes, bursts, head_ops = [], [], []
    for t in range(n_tenants):
        files = _daemon_tenant_files(N, R, E, OPF, seed=seed0 + t)
        n_tail = max(1, math.ceil(len(files) * 0.1))
        head, tail = files[:-n_tail], files[-n_tail:]
        remote = MemoryRemote()
        writer = await Core.open(opts_fn(MemoryStorage(remote)))
        for ab, v, ops in head:
            blob = await writer._seal(ops)
            await writer.storage.store_ops(ab, v, blob)
        head_ops.append(sum(len(ops) for _, _, ops in head))
        bursts.append([
            (ab, v, await writer._seal(ops), len(ops))
            for ab, v, ops in tail
        ])
        remotes.append(remote)
    return remotes, bursts, head_ops


def _daemon_opts_fn():
    from crdt_enc_tpu.backends import (
        PlainKeyCryptor, XChaChaCryptor,
    )
    from crdt_enc_tpu.core import OpenOptions, orset_adapter
    from crdt_enc_tpu.parallel import TpuAccelerator
    from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

    def opts(storage):
        return OpenOptions(
            storage=storage,
            cryptor=XChaChaCryptor(),
            key_cryptor=PlainKeyCryptor(),
            adapter=orset_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=True,
            accelerator=TpuAccelerator(),
        )
    return opts


def e2e_daemon_host(runs: int = 0):
    """Pinned host baseline for the daemon family (pin_baselines.py
    config 6): sequential solo ``Core.compact()`` over the default
    daemon fleet's HEAD shape (no churn — the pin is the steady-state
    denominator), median-of-N on fresh fleet copies per pass."""
    import asyncio
    import copy

    T, N, R, E, OPF, _ = _daemon_fleet_shape(smoke=False)
    opts = _daemon_opts_fn()

    async def build():
        return await _daemon_build_remotes(opts, T, N, R, E, OPF, 500)

    remotes, _bursts, head_ops = asyncio.run(build())
    total_ops = sum(head_ops)

    def run_once():
        async def one():
            from crdt_enc_tpu.backends import MemoryStorage
            from crdt_enc_tpu.core import Core

            cores = [
                await Core.open(opts(MemoryStorage(copy.deepcopy(r))))
                for r in remotes
            ]
            t0 = time.perf_counter()
            for c in cores:
                await c.compact()
            return time.perf_counter() - t0

        return asyncio.run(one()), None

    median_s, times, _ = host_median(run_once, runs)
    return {
        "config": f"daemon_{T}t",
        "host_rate": total_ops / median_s,
        "n_ops": total_ops,
        "shape": {"tenants": T, "ops_per_tenant": N, "replicas": R,
                  "members": E, "ops_per_file": OPF},
        "median_s": median_s,
        **host_stats(times),
    }


def e2e_daemon(smoke: bool):
    """ISSUE-12 acceptance: the always-on FleetDaemon under churn.

    T encrypted single-remote tenants are admitted into a
    :class:`~crdt_enc_tpu.serve.FleetDaemon` (staleness-driven
    scheduling: compaction is backlog-triggered, quiet tenants are
    stat-polled) and the daemon runs CYCLES supervised cycles while the
    fleet churns — T/8 tenants JOIN mid-run (admission), T/4 receive a
    ~10% op-tail BURST, T/8 are EVICTED with a final checkpoint.  The
    record is aggregate ops/s over the cycle loop, p99 freshness lag
    (the ``watermark_lag`` samples the scheduler itself consumed), and
    p99 per-tenant seal latency.  After the drain, every tenant's
    remote — including evicted ones — is refolded by a fresh solo
    ``Core.compact()`` on a copy; ANY byte divergence refuses the
    record (the standard e2e evidence guard).

    Env knobs: BENCH_DMN_TENANTS (256; --tenants N overrides),
    BENCH_DMN_OPS (256/tenant), BENCH_DMN_REPLICAS (4),
    BENCH_DMN_MEMBERS (64), BENCH_DMN_OPF (24), BENCH_DMN_CYCLES (6).
    """
    import asyncio
    import copy

    T, N, R, E, OPF, CYCLES = _daemon_fleet_shape(smoke)
    # T=1 evicts nobody: the burst target and the evictee would be the
    # same tenant, and an evictee with a fresh unfolded burst is stale
    # by construction — not a divergence the guard should compare
    JOIN, BURST = max(1, T // 8), max(1, T // 4)
    LEAVE = 0 if T == 1 else max(1, T // 8)

    platforms = os.environ.get("JAX_PLATFORMS", "").lower()
    first_platform = platforms.split(",")[0].strip() if platforms else ""
    want_tpu = first_platform not in ("cpu",) and not smoke
    jax, dev = acquire_jax(want_tpu)

    import crdt_enc_tpu
    from crdt_enc_tpu.backends import MemoryStorage
    from crdt_enc_tpu.core import Core
    from crdt_enc_tpu.models import canonical_bytes
    from crdt_enc_tpu.serve import DaemonConfig, FleetDaemon, ServeConfig
    from crdt_enc_tpu.utils import trace

    crdt_enc_tpu.enable_compilation_cache()
    opts = _daemon_opts_fn()

    async def scenario():
        remotes, bursts, head_ops = await _daemon_build_remotes(
            opts, T + JOIN, N, R, E, OPF, 500
        )
        log(
            f"e2e_daemon: device {dev.platform}; {T} tenants "
            f"(+{JOIN} join, -{LEAVE} evict, {BURST} burst), "
            f"{sum(head_ops[:T])} head ops"
        )
        cores = [
            await Core.open(opts(MemoryStorage(r))) for r in remotes[:T]
        ]
        cfg = DaemonConfig(
            interval_s=0.0, batch=T + JOIN,
            min_backlog_files=1, max_idle_cycles=CYCLES + 10,
            # admission sized to the fleet the scenario intends to
            # admit: the default warm-budget gate at the pre-
            # observation 1MiB/tenant estimate would refuse joiners
            # past 256 tenants (the operator's knob, set like one)
            admission_bytes=(T + JOIN + 1) << 20,
            serve=ServeConfig(seal_empty=False),
        )
        daemon = FleetDaemon(cores, cfg, seed=7)

        # warmup compiles on a throwaway copy fleet (repo protocol)
        warm = [
            await Core.open(opts(MemoryStorage(copy.deepcopy(r))))
            for r in remotes[: min(8, T)]
        ]
        await daemon.service.run_cycle(warm)
        del warm

        total_ops = sum(head_ops[:T])
        seal_lat: list = []
        fresh_lag: list = []
        churn = {"joined": 0, "evicted": 0, "burst_tenants": 0,
                 "burst_ops": 0}
        trace.reset()
        t0 = time.perf_counter()
        for c in range(CYCLES):
            if c == 1:  # joiners: admission while running
                for j in range(JOIN):
                    core = await Core.open(
                        opts(MemoryStorage(remotes[T + j]))
                    )
                    await daemon.admit(core)
                    cores.append(core)
                    total_ops += head_ops[T + j]
                    churn["joined"] += 1
            if c == 2:  # burst: op tails land on live tenants
                for t in range(BURST):
                    # distinct targets past the future evictees (wraps
                    # only at T=1, where BURST is also 1)
                    idx = (LEAVE + t) % T
                    core = cores[idx]
                    for ab, v, blob, n_ops in bursts[idx]:
                        await core.storage.store_ops(ab, v, blob)
                        total_ops += n_ops
                        churn["burst_ops"] += n_ops
                    churn["burst_tenants"] += 1
            if c == 3:  # leavers: eviction with a final checkpoint
                for t in range(LEAVE):
                    await daemon.evict(f"t{t}")
                    churn["evicted"] += 1
            report = await daemon.run_cycle()
            for res in report["results"].values():
                if res.get("latency_s") is not None:
                    seal_lat.append(res["latency_s"])
            for tid in daemon.tenant_ids:
                status = daemon.entry(tid).status()
                if status is not None:
                    fresh_lag.append(
                        float(status["divergence"]["watermark_lag"])
                    )
        wall = time.perf_counter() - t0
        obs = trace.snapshot()
        await daemon.drain()

        # the no-divergence guard: EVERY tenant's remote (evicted ones
        # included) must refold solo to the daemon tenant's final state
        diverged = []
        for i, core in enumerate(cores):
            solo = await Core.open(
                opts(MemoryStorage(copy.deepcopy(remotes[i])))
            )
            await solo.compact()
            if solo.with_state(canonical_bytes) != core.with_state(
                canonical_bytes
            ):
                diverged.append(i)
        return (
            wall, total_ops, seal_lat, fresh_lag, churn, obs, diverged,
            daemon.health(),
        )

    (wall, total_ops, seal_lat, fresh_lag, churn, obs, diverged,
     health) = asyncio.run(scenario())

    rate = total_ops / wall
    # freshness lag is in VERSIONS (not a latency) — exact nearest-rank
    q = _nearest_rank

    result = {
        "metric": "daemon_e2e_agg_ops_per_sec",
        "config": f"daemon_{T}t",
        "value": round(rate, 1),
        "unit": "ops/s",
        "cycles": CYCLES,
        "wall_s": round(wall, 4),
        "total_ops": total_ops,
        "seal_latency": _quantiles_ms(seal_lat) if seal_lat else {},
        "freshness_lag_versions": {
            "p50": q(fresh_lag, 0.50), "p99": q(fresh_lag, 0.99),
            "max": max(fresh_lag),
        } if fresh_lag else {},
        "churn": churn,
        "daemon": {k: health[k] for k in
                   ("cycles", "tenants", "quarantined", "degraded")},
        "byte_identical": not diverged,
        "backend": dev.platform,
    }
    pin_shape = {"tenants": T, "ops_per_tenant": N, "replicas": R,
                 "members": E, "ops_per_file": OPF}
    pin = load_pinned(f"daemon_{T}t", pin_shape)
    if pin:
        result["vs_pinned_baseline"] = round(rate / pin["host_rate"], 2)
        result["pinned_host_rate"] = pin["host_rate"]
        result["vs_baseline"] = result["vs_pinned_baseline"]
    print(json.dumps(result))
    if diverged:
        log(
            f"FAILED: tenants {diverged[:5]} diverged from solo "
            "compact() — refusing to record"
        )
        raise SystemExit(1)
    if os.environ.get("BENCH_LOCAL_DISABLE") == "1":
        return
    if dev.platform != "tpu" and os.environ.get("BENCH_LOCAL_ALL") != "1":
        return
    _append_local({
        **result,
        "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"),
        "device_kind": dev.device_kind,
        "host_cpus": os.cpu_count(),
        "shape": {**pin_shape, "cycles": CYCLES, "join": JOIN,
                  "leave": LEAVE, "burst": BURST},
        "obs": obs,
    })


def e2e_idle_cycle(smoke: bool):
    """ISSUE-16 acceptance: the O(tail) steady state.

    A T-tenant fleet (the daemon shape, BENCH_DMN_* knobs) is folded
    once to seed warm planes + delta bases, then served at three ACTIVE
    FRACTIONS — 100%, 10%, 1% of tenants receiving one new op file per
    cycle — under two arms:

    * ``continuation`` — the default :class:`ServeConfig`: warm planes
      are the fold accumulator, quiet tenants no-op via the seal
      signature (``serve_noop_cycles``), active tenants seal deltas by
      device cut (``delta_device_cuts``).
    * ``full_refold`` — ``ServeConfig(warm=False, noop_skip=False)``:
      the O(state) steady state every cycle (quiet tenants re-seal
      their whole snapshot; actives refold from the stored base).

    The record's headline value is the 1%-active cycle-wall ratio
    full_refold/continuation (≥10x is the ISSUE-16 bar).  Per-fraction
    rows carry wall/cycle, per-quiet-tenant cost (an all-quiet cycle /
    T), ``jax_compiles`` and ``h2d_bytes`` deltas over the measured
    window, ``serve_noop_cycles`` and ``delta_base_bytes``.  After the
    run EVERY tenant in BOTH arms must byte-match a fresh solo
    ``Core.compact()`` of its remote — divergence refuses the record
    (the standard e2e evidence guard)."""
    import asyncio
    import copy

    T, N, R, E, OPF, _ = _daemon_fleet_shape(smoke)
    FRACTIONS = (1.0, 0.1, 0.01)
    CYC = int(os.environ.get("BENCH_IDLE_CYCLES", 2 if smoke else 3))

    platforms = os.environ.get("JAX_PLATFORMS", "").lower()
    first_platform = platforms.split(",")[0].strip() if platforms else ""
    want_tpu = first_platform not in ("cpu",) and not smoke
    jax, dev = acquire_jax(want_tpu)

    import crdt_enc_tpu
    from crdt_enc_tpu.backends import (
        MemoryRemote, MemoryStorage, PlainKeyCryptor, XChaChaCryptor,
    )
    from crdt_enc_tpu.core import Core, OpenOptions, orset_adapter
    from crdt_enc_tpu.models import canonical_bytes
    from crdt_enc_tpu.obs import runtime as obs_runtime
    from crdt_enc_tpu.parallel import TpuAccelerator
    from crdt_enc_tpu.serve import FoldService, ServeConfig
    from crdt_enc_tpu.utils import trace
    from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

    crdt_enc_tpu.enable_compilation_cache()
    obs_runtime.track_recompiles()

    def opts(storage):
        return OpenOptions(
            storage=storage,
            cryptor=XChaChaCryptor(),
            key_cryptor=PlainKeyCryptor(),
            adapter=orset_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=True,
            accelerator=TpuAccelerator(),
            delta=True,
        )

    # one drip file per active tenant per cycle: CYC measured cycles
    # plus one untimed warmup cycle per fraction (the warmup settles
    # the fraction's compile classes so the measured window is
    # steady-state, not compile wall)
    need_drip = len(FRACTIONS) * (CYC + 1)

    async def build():
        from benchmarks.suite import actor_bytes_table

        # the drip writer is its own actor (one PAST the R plane
        # replicas) so drip file versions never collide with head files
        drip_ab = actor_bytes_table(R + 1)[R]
        remotes, drips = [], []
        for t in range(T):
            files = _daemon_tenant_files(N, R, E, OPF, seed=900 + t)
            # take files from the end until the tail holds at least one
            # op per drip file (but always keep one head file)
            n_tail, got = 0, 0
            while got < need_drip and n_tail < len(files) - 1:
                n_tail += 1
                got += len(files[-n_tail][2])
            n_tail = max(n_tail, min(len(files) - 1, len(files) // 3))
            head, tail = files[:-n_tail], files[-n_tail:]
            # re-chunk the tail's ops into exactly need_drip files (the
            # op payload carries its own dot, so the drip writer can
            # relay any actor's ops)
            tail_ops = [op for _ab, _v, ops in tail for op in ops]
            if len(tail_ops) < need_drip or not head:
                raise SystemExit(
                    f"shape too small: tenant {t} has {len(tail_ops)} "
                    f"tail ops for a {need_drip}-file drip schedule"
                )
            step = len(tail_ops) / need_drip
            cuts = [round(i * step) for i in range(need_drip + 1)]
            remote = MemoryRemote()
            writer = await Core.open(opts(MemoryStorage(remote)))
            for ab, v, ops in head:
                blob = await writer._seal(ops)
                await writer.storage.store_ops(ab, v, blob)
            drips.append([
                (drip_ab, i + 1,
                 await writer._seal(tail_ops[cuts[i]:cuts[i + 1]]))
                for i in range(need_drip)
            ])
            remotes.append(remote)
        return remotes, drips

    remotes, drips = asyncio.run(build())
    log(
        f"e2e_idle_cycle: device {dev.platform}; {T} tenants, "
        f"{CYC} cycles/fraction, fractions {FRACTIONS}"
    )

    async def run_arm(arm: str):
        cfg = (ServeConfig() if arm == "continuation"
               else ServeConfig(warm=False, noop_skip=False))
        arm_remotes = [copy.deepcopy(r) for r in remotes]
        cores = [
            await Core.open(opts(MemoryStorage(r))) for r in arm_remotes
        ]
        service = FoldService(cores, cfg)
        # seed cycle: folds every head, seals, stamps continuations
        await service.run_cycle()
        await service.run_cycle()  # settle compiles on the quiet shape

        drip_pos = [0] * T
        fraction_rows = []
        obs_1pct = None
        for frac in FRACTIONS:
            n_active = max(1, round(T * frac))

            async def drip_actives():
                for t in range(n_active):
                    ab, v, blob = drips[t][drip_pos[t]]
                    drip_pos[t] += 1
                    await cores[t].storage.store_ops(ab, v, blob)

            # untimed warmup at THIS fraction's bucket shape
            await drip_actives()
            await service.run_cycle()
            trace.reset()
            walls = []
            for _c in range(CYC):
                await drip_actives()
                t0 = time.perf_counter()
                await service.run_cycle()
                walls.append(time.perf_counter() - t0)
            counters = trace.snapshot()["counters"]
            gauges = trace.snapshot()["gauges"]
            # all-quiet cycle: the pure per-quiet-tenant marginal
            tq = time.perf_counter()
            await service.run_cycle()
            quiet_wall = time.perf_counter() - tq
            row = {
                "active_fraction": frac,
                "active_tenants": n_active,
                "wall_per_cycle_s": round(sorted(walls)[len(walls) // 2], 5),
                "quiet_cycle_s": round(quiet_wall, 5),
                "per_quiet_tenant_us": round(quiet_wall / T * 1e6, 2),
                "jax_compiles": counters.get("jax_compiles", 0),
                "h2d_bytes": counters.get("h2d_bytes", 0),
                "serve_noop_cycles": counters.get("serve_noop_cycles", 0),
                "delta_device_cuts": counters.get("delta_device_cuts", 0),
                "delta_base_bytes": gauges.get("delta_base_bytes"),
            }
            if frac == 0.01 and arm == "continuation":
                obs_1pct = trace.snapshot()
            fraction_rows.append(row)

        # fold any unused drip files so both arms end byte-comparable,
        # then guard: every tenant must match a fresh solo compact
        for t in range(T):
            while drip_pos[t] < need_drip:
                ab, v, blob = drips[t][drip_pos[t]]
                drip_pos[t] += 1
                await cores[t].storage.store_ops(ab, v, blob)
        await service.run_cycle()
        diverged = []
        for i, core in enumerate(cores):
            solo = await Core.open(
                opts(MemoryStorage(copy.deepcopy(arm_remotes[i])))
            )
            await solo.compact()
            if solo.with_state(canonical_bytes) != core.with_state(
                canonical_bytes
            ):
                diverged.append(i)
        service.close()
        return fraction_rows, diverged, obs_1pct

    async def scenario():
        cont, div_c, obs_1pct = await run_arm("continuation")
        full, div_f, _ = await run_arm("full_refold")
        return cont, full, div_c + div_f, obs_1pct

    cont, full, diverged, obs_1pct = asyncio.run(scenario())

    by_frac = {r["active_fraction"]: r for r in full}
    speedup = round(
        by_frac[0.01]["wall_per_cycle_s"]
        / max(cont[-1]["wall_per_cycle_s"], 1e-9), 2
    )
    result = {
        "metric": "idle_cycle_speedup",
        "config": f"idle_{T}t",
        "value": speedup,
        "unit": "x_at_1pct_active",
        "continuation": cont,
        "full_refold": full,
        "byte_identical": not diverged,
        "backend": dev.platform,
    }
    print(json.dumps(result))
    if diverged:
        log(
            f"FAILED: tenants {sorted(set(diverged))[:5]} diverged from "
            "solo compact() — refusing to record"
        )
        raise SystemExit(1)
    if os.environ.get("BENCH_LOCAL_DISABLE") == "1":
        return
    if dev.platform != "tpu" and os.environ.get("BENCH_LOCAL_ALL") != "1":
        return
    _append_local({
        **result,
        "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"),
        "device_kind": dev.device_kind,
        "host_cpus": os.cpu_count(),
        "shape": {"tenants": T, "ops_per_tenant": N, "replicas": R,
                  "members": E, "ops_per_file": OPF, "cycles": CYC},
        "obs": obs_1pct,
    })


def e2e_warm_open(smoke: bool):
    """ISSUE-4 acceptance: cold open vs checkpointed (warm) open of a
    config-5-shaped un-compacted remote with a 1% op tail.

    A real FS remote is populated with N three-layer-sealed op files
    across R actors; replica A reads it all once and seals a local fold
    checkpoint.  Then a 1% tail of new op files lands and we measure,
    on the SAME remote:

    * **cold** — a fresh replica (no local state) opens and refolds the
      entire history through the streaming ingest, and
    * **warm** — replica A reopens: the checkpoint restores the
      materialized state + cursor and only the tail is decrypted,
      decoded and folded.

    Byte equality of the two resulting states is asserted, both obs
    snapshots are recorded, and a two-round-compact h2d_bytes sample
    proves the device-resident plane reuse (round 2 re-uploads no
    full-state planes).  Appends the record to BENCH_LOCAL.jsonl
    (BENCH_LOCAL_ALL=1 to record CPU runs).

    Env knobs: BENCH_WARM_OPS (1_000_000), BENCH_WARM_REPLICAS (10_000),
    BENCH_WARM_MEMBERS (1024), BENCH_WARM_OPF (48, ops per file),
    BENCH_WARM_TAIL_PCT (1.0).
    """
    import asyncio
    import tempfile

    N = int(os.environ.get("BENCH_WARM_OPS", 20_000 if smoke else 1_000_000))
    R = int(os.environ.get("BENCH_WARM_REPLICAS", 200 if smoke else 10_000))
    E = int(os.environ.get("BENCH_WARM_MEMBERS", 128 if smoke else 1024))
    OPF = int(os.environ.get("BENCH_WARM_OPF", 48))
    TAIL_PCT = float(os.environ.get("BENCH_WARM_TAIL_PCT", 1.0))

    platforms = os.environ.get("JAX_PLATFORMS", "").lower()
    first_platform = platforms.split(",")[0].strip() if platforms else ""
    want_tpu = first_platform not in ("cpu",) and not smoke
    jax, dev = acquire_jax(want_tpu)

    import crdt_enc_tpu
    from benchmarks.suite import actor_bytes_table
    from crdt_enc_tpu.backends import (
        FsStorage, PlainKeyCryptor, XChaChaCryptor,
    )
    from crdt_enc_tpu.core import Core, OpenOptions, orset_adapter
    from crdt_enc_tpu.models import canonical_bytes
    from crdt_enc_tpu.parallel import TpuAccelerator
    from crdt_enc_tpu.utils import trace
    from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

    crdt_enc_tpu.enable_compilation_cache()

    def opts(storage, create):
        return OpenOptions(
            storage=storage,
            cryptor=XChaChaCryptor(),
            key_cryptor=PlainKeyCryptor(),
            adapter=orset_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=create,
            accelerator=TpuAccelerator(),
        )

    # ---- per-actor op files from the config-3/5 column generator,
    # sealed in the core's real three-layer wire format
    kind, member, actor, counter = gen_columns(N, R, E, seed=11)
    actors = actor_bytes_table(R)
    live = actor < R
    order = np.argsort(actor[live], kind="stable")
    k_l = kind[live][order]
    m_l = member[live][order]
    a_l = actor[live][order]
    c_l = counter[live][order]

    def file_payloads():
        """Yield (actor_bytes, version, ops_obj) per file, versions dense
        from 1 per actor."""
        i, n = 0, len(k_l)
        versions: dict = {}
        while i < n:
            j = min(i + OPF, n)
            j = i + int(np.searchsorted(a_l[i:j], a_l[i], side="right"))
            ab = actors[int(a_l[i])]
            ops = []
            for t in range(i, j):
                if k_l[t] == 0:
                    ops.append([0, int(m_l[t]), [ab, int(c_l[t])]])
                else:
                    ops.append([1, int(m_l[t]), {ab: int(c_l[t])}])
            v = versions.get(ab, 0) + 1
            versions[ab] = v
            yield ab, v, ops
            i = j

    files = list(file_payloads())
    # the TAIL_PCT% op tail: final files (one per contributing actor)
    # held back until the checkpoint is sealed, accumulating actors
    # until the tail holds ~TAIL_PCT% of all ops
    total_ops = sum(len(ops) for _, _, ops in files)
    last_file_idx = {}
    for idx, (ab, v, _) in enumerate(files):
        last_file_idx[ab] = idx
    target_ops = max(1, int(total_ops * TAIL_PCT / 100.0))
    tail_idx: set = set()
    n_tail_ops = 0
    for ab in actors:
        idx = last_file_idx.get(ab)
        if idx is None:
            continue
        tail_idx.add(idx)
        n_tail_ops += len(files[idx][2])
        if n_tail_ops >= target_ops:
            break
    prefix = [f for i, f in enumerate(files) if i not in tail_idx]
    tail = [f for i, f in enumerate(files) if i in tail_idx]

    tmp = tempfile.mkdtemp(prefix="crdt-warm-open-")
    remote = os.path.join(tmp, "remote")
    log(
        f"e2e_warm_open: device {dev.platform}; {len(files)} files "
        f"({len(tail)} tail), {total_ops} ops ({n_tail_ops} tail), "
        f"R={R} E={E} remote={remote}"
    )

    async def build_and_measure():
        storage_a = FsStorage(os.path.join(tmp, "localA"), remote)
        core_a = await Core.open(opts(storage_a, create=True))

        async def store_files(batch):
            sem = asyncio.Semaphore(64)

            async def one(ab, v, ops):
                async with sem:
                    blob = await core_a._seal(ops)
                    await core_a.storage.store_ops(ab, v, blob)

            await asyncio.gather(*(one(*f) for f in batch))

        t0 = time.perf_counter()
        CHUNK = 2048  # bound in-flight seal buffers
        for i in range(0, len(prefix), CHUNK):
            await store_files(prefix[i : i + CHUNK])
        t_build = time.perf_counter() - t0
        log(f"remote built: {len(prefix)} files in {t_build:.1f}s")

        # replica A folds the full history once and seals its resume point
        t0 = time.perf_counter()
        await core_a.read_remote()
        t_first = time.perf_counter() - t0
        trace.reset()
        await core_a.save_checkpoint()
        ck_bytes = trace.snapshot()["counters"].get("checkpoint_bytes", 0)
        log(f"first full fold: {t_first:.2f}s; checkpoint sealed "
            f"({ck_bytes} bytes)")

        await store_files(tail)

        # ---- cold: a fresh replica refolds EVERYTHING
        trace.reset()
        t0 = time.perf_counter()
        core_cold = await Core.open(
            opts(FsStorage(os.path.join(tmp, "localB"), remote), create=True)
        )
        await core_cold.read_remote()
        t_cold = time.perf_counter() - t0
        obs_cold = trace.snapshot()

        # ---- warm: replica A reopens from its checkpoint + 1% tail
        trace.reset()
        t0 = time.perf_counter()
        core_warm = await Core.open(
            opts(FsStorage(os.path.join(tmp, "localA"), remote), create=False)
        )
        warm_hit = core_warm.opened_from_checkpoint
        await core_warm.read_remote()
        t_warm = time.perf_counter() - t0
        obs_warm = trace.snapshot()

        equal = core_cold.with_state(canonical_bytes) == core_warm.with_state(
            canonical_bytes
        )
        return (
            t_build, t_first, t_cold, t_warm, warm_hit, equal,
            obs_cold, obs_warm, core_warm.checkpoint_fallback_reason,
            ck_bytes,
        )

    (t_build, t_first, t_cold, t_warm, warm_hit, equal, obs_cold, obs_warm,
     fallback, ck_bytes) = asyncio.run(build_and_measure())

    # ---- device-resident plane reuse: two-round compact h2d sample
    plane_proof = asyncio.run(_plane_reuse_rounds())

    speedup = t_cold / t_warm
    log(
        f"cold open {t_cold:.2f}s vs warm open {t_warm:.3f}s → "
        f"{speedup:.1f}x (warm hit: {warm_hit}, equal: {equal})"
    )
    result = {
        "metric": "orset_e2e_warm_open_speedup",
        "config": f"warm_open_{N}ops_{R}r_{TAIL_PCT:g}pct_tail",
        "value": round(speedup, 2),
        "unit": "x",
        "cold_open_s": round(t_cold, 4),
        "warm_open_s": round(t_warm, 4),
        "first_fold_s": round(t_first, 4),
        "build_s": round(t_build, 1),
        "opened_from_checkpoint": bool(warm_hit),
        "checkpoint_fallback_reason": fallback,
        "byte_identical": bool(equal),
        "checkpoint_bytes": ck_bytes,
        "plane_reuse": {
            k: v for k, v in plane_proof.items() if k != "obs"
        },
        "backend": dev.platform,
    }
    print(json.dumps(result))
    # the bench exists to prove these — a run that silently fell back to
    # a cold open or diverged must fail loudly (diagnostic JSON above is
    # printed, but nothing lands in the evidence file)
    if not (warm_hit and equal):
        log(
            f"FAILED: warm_hit={warm_hit} (fallback: {fallback}) "
            f"byte_identical={equal} — refusing to record"
        )
        raise SystemExit(1)
    if os.environ.get("BENCH_LOCAL_DISABLE") != "1" and (
        dev.platform == "tpu" or os.environ.get("BENCH_LOCAL_ALL") == "1"
    ):
        _append_local({
            **result,
            "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(
                timespec="seconds"),
            "device_kind": dev.device_kind,
            "host_cpus": os.cpu_count(),
            "shape": {"N": N, "R": R, "E": E, "ops_per_file": OPF,
                      "files": len(files), "tail_files": len(tail),
                      "tail_ops": n_tail_ops, "total_ops": total_ops},
            "obs_cold": obs_cold,
            "obs_warm": obs_warm,
            "obs_plane_reuse": plane_proof.get("obs"),
        })


async def _plane_reuse_rounds():
    """Two compaction rounds in one process on a small dense-regime
    workload: round 1 uploads the full state planes (counted in
    h2d_bytes at issue), round 2 hits the accelerator's device-resident
    plane cache — ~zero full-state re-upload (ISSUE-4 acceptance)."""
    from crdt_enc_tpu.backends import (
        IdentityCryptor, MemoryRemote, MemoryStorage, PlainKeyCryptor,
    )
    from crdt_enc_tpu.core import Core, OpenOptions, orset_adapter
    from crdt_enc_tpu.parallel import TpuAccelerator
    from crdt_enc_tpu.utils import trace
    from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

    def opts(storage, accel=None):
        return OpenOptions(
            storage=storage, cryptor=IdentityCryptor(),
            key_cryptor=PlainKeyCryptor(), adapter=orset_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1, create=True,
            accelerator=accel if accel is not None else TpuAccelerator(),
        )

    remote = MemoryRemote()
    reader = await Core.open(
        opts(MemoryStorage(remote), TpuAccelerator(min_device_batch=1))
    )
    writer = await Core.open(opts(MemoryStorage(remote)))

    async def write(n, tag):
        for i in range(n):
            await writer.apply_ops([writer.with_state(
                lambda s: s.add_ctx(writer.actor_id, b"%s-%d" % (tag, i))
            )])

    rounds = {}
    for rd in (1, 2):
        await write(60, b"r%d" % rd)
        trace.reset()
        await reader.compact()
        snap = trace.snapshot()
        rounds[rd] = {
            "h2d_bytes": snap["counters"].get("h2d_bytes", 0),
            "obs": snap,
        }
    return {
        "round1_h2d_bytes": rounds[1]["h2d_bytes"],
        "round2_h2d_bytes": rounds[2]["h2d_bytes"],
        "round2_full_state_reupload": rounds[2]["h2d_bytes"] > 0,
        "obs": rounds[2]["obs"],
    }


def _flag_int(flag: str, default: int) -> int:
    """``--flag N`` from argv, else ``default`` (the --tenants pattern,
    shared by the sim sweep's --replicas/--steps)."""
    if flag in sys.argv:
        i = sys.argv.index(flag)
        if i + 1 < len(sys.argv):
            try:
                n = int(sys.argv[i + 1])
            except ValueError:
                raise SystemExit(f"{flag} wants N, got {sys.argv[i + 1]!r}")
            if n > 0:
                return n
        raise SystemExit(f"{flag} wants a positive count")
    return default


class _CountingStorage:
    """Wrap a Storage, counting every remote payload byte the core
    reads (states + op files + deltas) — the e2e-delta bench's
    measurement instrument.  Everything else forwards untouched."""

    def __init__(self, inner):
        self._inner = inner
        self.bytes_read = 0
        self.files_read = 0

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def _count(self, loaded):
        for item in loaded:
            self.bytes_read += len(item[-1])
            self.files_read += 1
        return loaded

    async def load_states(self, names):
        return self._count(await self._inner.load_states(names))

    async def load_ops(self, wanted):
        return self._count(await self._inner.load_ops(wanted))

    async def load_deltas(self, wanted):
        return self._count(await self._inner.load_deltas(wanted))

    async def iter_op_chunks(self, wanted, max_bytes=None):
        kw = {} if max_bytes is None else {"max_bytes": max_bytes}
        async for chunk in self._inner.iter_op_chunks(wanted, **kw):
            yield self._count(chunk)


def e2e_delta(smoke: bool):
    """ISSUE-10 acceptance: remote bytes read by an INCREMENTAL consumer
    — delta-chain path vs full-snapshot path — on the same remote.

    One producer builds a real three-layer-sealed FS remote, folds it,
    and compacts (snapshot + delta per round, docs/delta.md).  Two
    consumers track it: A with delta-state replication on (folds
    ``known-base + delta chain``), B with it off (re-downloads the full
    snapshot every round).  Each round lands a ~BENCH_DELTA_TAIL_PCT%
    op tail before the producer compacts again.  The record is the
    bytes-read reduction A/B plus wall times; byte-identity of all
    three states is ASSERTED and the run refuses to record otherwise
    (the divergence guard every e2e bench carries).

    Env knobs: BENCH_DELTA_OPS (200_000), BENCH_DELTA_REPLICAS (2_000),
    BENCH_DELTA_MEMBERS (512), BENCH_DELTA_OPF (48, ops/file),
    BENCH_DELTA_ROUNDS (5), BENCH_DELTA_TAIL_PCT (1.0).
    """
    import asyncio
    import tempfile

    N = int(os.environ.get("BENCH_DELTA_OPS", 6_000 if smoke else 200_000))
    R = int(os.environ.get("BENCH_DELTA_REPLICAS", 60 if smoke else 2_000))
    E = int(os.environ.get("BENCH_DELTA_MEMBERS", 64 if smoke else 512))
    OPF = int(os.environ.get("BENCH_DELTA_OPF", 48))
    ROUNDS = int(os.environ.get("BENCH_DELTA_ROUNDS", 2 if smoke else 5))
    TAIL_PCT = float(os.environ.get("BENCH_DELTA_TAIL_PCT", 1.0))

    platforms = os.environ.get("JAX_PLATFORMS", "").lower()
    first_platform = platforms.split(",")[0].strip() if platforms else ""
    want_tpu = first_platform not in ("cpu",) and not smoke
    jax, dev = acquire_jax(want_tpu)

    from benchmarks.suite import actor_bytes_table
    from crdt_enc_tpu.backends import (
        FsStorage, PlainKeyCryptor, XChaChaCryptor,
    )
    from crdt_enc_tpu.core import Core, OpenOptions, orset_adapter
    from crdt_enc_tpu.models import canonical_bytes
    from crdt_enc_tpu.parallel import TpuAccelerator
    from crdt_enc_tpu.utils import trace
    from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1

    def opts(storage, create, delta=True):
        return OpenOptions(
            storage=storage,
            cryptor=XChaChaCryptor(),
            key_cryptor=PlainKeyCryptor(),
            adapter=orset_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=create,
            accelerator=TpuAccelerator(),
            delta=delta,
        )

    kind, member, actor, counter = gen_columns(N, R, E, seed=23)
    actors = actor_bytes_table(R)
    live = actor < R
    order = np.argsort(actor[live], kind="stable")
    k_l = kind[live][order]
    m_l = member[live][order]
    a_l = actor[live][order]
    c_l = counter[live][order]

    def file_payloads():
        i, n = 0, len(k_l)
        versions: dict = {}
        while i < n:
            j = min(i + OPF, n)
            j = i + int(np.searchsorted(a_l[i:j], a_l[i], side="right"))
            ab = actors[int(a_l[i])]
            ops = []
            for t in range(i, j):
                if k_l[t] == 0:
                    ops.append([0, int(m_l[t]), [ab, int(c_l[t])]])
                else:
                    ops.append([1, int(m_l[t]), {ab: int(c_l[t])}])
            v = versions.get(ab, 0) + 1
            versions[ab] = v
            yield ab, v, ops
            i = j
        # the per-round incremental tails continue each actor's log
        while True:
            target = max(1, int(N * TAIL_PCT / 100.0))
            got = 0
            batch = []
            for ab in actors:
                if got >= target:
                    break
                v = versions.get(ab, 0) + 1
                versions[ab] = v
                ops = [
                    [0, int((v * 37 + t) % E), [ab, 1_000_000 + v * OPF + t]]
                    for t in range(min(OPF, target - got))
                ]
                got += len(ops)
                batch.append((ab, v, ops))
            yield ("round", batch)

    gen = file_payloads()
    prefix = []
    for item in gen:
        if isinstance(item[0], str):
            break
        prefix.append(item)

    tmp = tempfile.mkdtemp(prefix="crdt-e2e-delta-")
    remote = os.path.join(tmp, "remote")
    log(
        f"e2e_delta: device {dev.platform}; {len(prefix)} files, {N} ops, "
        f"R={R} E={E} rounds={ROUNDS} tail={TAIL_PCT:g}% remote={remote}"
    )

    async def build_and_measure():
        producer = await Core.open(
            opts(FsStorage(os.path.join(tmp, "localP"), remote), create=True)
        )

        async def store_files(batch):
            sem = asyncio.Semaphore(64)

            async def one(ab, v, ops):
                async with sem:
                    blob = await producer._seal(ops)
                    await producer.storage.store_ops(ab, v, blob)

            await asyncio.gather(*(one(*f) for f in batch))

        t0 = time.perf_counter()
        CHUNK = 2048
        for i in range(0, len(prefix), CHUNK):
            await store_files(prefix[i : i + CHUNK])
        t_build = time.perf_counter() - t0
        await producer.compact()
        log(f"remote built + first compact: {t_build:.1f}s")

        storage_a = _CountingStorage(
            FsStorage(os.path.join(tmp, "localA"), remote)
        )
        storage_b = _CountingStorage(
            FsStorage(os.path.join(tmp, "localB"), remote)
        )
        c_delta = await Core.open(opts(storage_a, create=True))
        c_snap = await Core.open(opts(storage_b, create=True, delta=False))
        await c_delta.read_remote()
        await c_snap.read_remote()
        # the incremental phase is the measurement window
        storage_a.bytes_read = storage_a.files_read = 0
        storage_b.bytes_read = storage_b.files_read = 0
        trace.reset()
        t_delta = t_snap = 0.0
        for _ in range(ROUNDS):
            tag, batch = next(gen)
            assert tag == "round"
            await store_files(batch)
            await producer.compact()
            t0 = time.perf_counter()
            await c_delta.read_remote()
            t_delta += time.perf_counter() - t0
            t0 = time.perf_counter()
            await c_snap.read_remote()
            t_snap += time.perf_counter() - t0
        obs = trace.snapshot()
        pa = producer.with_state(canonical_bytes)
        equal = (
            c_delta.with_state(canonical_bytes) == pa
            and c_snap.with_state(canonical_bytes) == pa
        )
        return (
            t_build, t_delta, t_snap, equal,
            storage_a.bytes_read, storage_b.bytes_read,
            storage_a.files_read, storage_b.files_read, obs,
        )

    (t_build, t_delta, t_snap, equal, bytes_delta, bytes_snap,
     files_delta, files_snap, obs) = asyncio.run(build_and_measure())

    counters = obs.get("counters", {})
    applied = counters.get("delta_applied", 0)
    reduction = bytes_snap / bytes_delta if bytes_delta else float("inf")
    log(
        f"incremental consumer over {ROUNDS} rounds: delta path "
        f"{bytes_delta}B / snapshot path {bytes_snap}B → {reduction:.1f}x "
        f"fewer remote bytes (chains applied: {applied}; "
        f"wall {t_delta:.2f}s vs {t_snap:.2f}s)"
    )
    result = {
        "metric": "orset_e2e_delta_bytes_reduction",
        "config": f"delta_{N}ops_{R}r_{ROUNDS}x{TAIL_PCT:g}pct_tail",
        "value": round(reduction, 2),
        "unit": "x",
        "bytes_read_delta_path": int(bytes_delta),
        "bytes_read_snapshot_path": int(bytes_snap),
        "files_read_delta_path": int(files_delta),
        "files_read_snapshot_path": int(files_snap),
        "read_wall_delta_s": round(t_delta, 4),
        "read_wall_snapshot_s": round(t_snap, 4),
        "build_s": round(t_build, 1),
        "deltas_applied": int(applied),
        "deltas_sealed": int(counters.get("delta_files_sealed", 0)),
        "delta_bytes_sealed": int(counters.get("delta_bytes_sealed", 0)),
        "delta_fallbacks": int(counters.get("delta_fallbacks", 0)),
        "byte_identical": bool(equal),
        "backend": dev.platform,
    }
    print(json.dumps(result))
    # the divergence guard: a run whose delta path did not converge
    # byte-identically (or never used the chain) proves nothing and
    # must not become perf evidence
    if not equal or applied < ROUNDS:
        log(
            f"FAILED: byte_identical={equal} chains_applied={applied}/"
            f"{ROUNDS} — refusing to record"
        )
        raise SystemExit(1)
    if os.environ.get("BENCH_LOCAL_DISABLE") == "1":
        return
    if dev.platform != "tpu" and os.environ.get("BENCH_LOCAL_ALL") != "1":
        return
    _append_local({
        **result,
        "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"),
        "device_kind": dev.device_kind,
        "host_cpus": os.cpu_count(),
        "shape": {"N": N, "R": R, "E": E, "ops_per_file": OPF,
                  "rounds": ROUNDS, "tail_pct": TAIL_PCT},
        "obs": obs,
    })


def e2e_strong_read(smoke: bool):
    """ISSUE-15 acceptance: linearizable point reads at the stability
    watermark under producer churn (docs/strong_reads.md).

    R producer replicas and one reader share an XChaCha-encrypted
    remote.  Each round every producer seals a wave of op files and —
    on a staggered cadence — compacts (publishing its cursor, which is
    what advances the watermark); the reader interleaves EVENTUAL reads
    (``read_remote`` + ``Core.read()``) with STRONG reads
    (``Core.read(linearizable=True)``, which refreshes, recomputes the
    watermark and advances the stable prefix), sampling the
    watermark-advance lag (union versions ahead of the served frontier)
    at every strong read plus an untimed ``max_lag=0`` refusal probe
    (``refusals`` = how often a zero-staleness caller would have been
    refused under this churn).  The record is strong reads/s with
    p50/p99 latency for both tiers and the lag distribution — the
    price of the guarantee, measured, not asserted.

    Evidence guard: the final strong read (everything published) must
    be byte-identical to a pure-Python oracle fold of exactly the cut
    it names — ANY divergence refuses the record.  Protocol-level and
    CPU-bound by design (the fold tails are host-side), so records land
    in BENCH_LOCAL.jsonl without the TPU gate, like ``--sim``.

    Env knobs: BENCH_SR_PRODUCERS (4), BENCH_SR_ROUNDS (6),
    BENCH_SR_WAVE (24 ops/producer/round), BENCH_SR_READS (6
    strong+eventual pairs/round), BENCH_SR_PUB_EVERY (2 — rounds
    between a producer's cursor publications).
    """
    import asyncio

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    R = int(os.environ.get("BENCH_SR_PRODUCERS", 2 if smoke else 4))
    ROUNDS = int(os.environ.get("BENCH_SR_ROUNDS", 2 if smoke else 6))
    WAVE = int(os.environ.get("BENCH_SR_WAVE", 8 if smoke else 24))
    READS = int(os.environ.get("BENCH_SR_READS", 2 if smoke else 6))
    PUB_EVERY = int(os.environ.get("BENCH_SR_PUB_EVERY", 2))

    from crdt_enc_tpu.backends import MemoryRemote, MemoryStorage
    from crdt_enc_tpu.core import Core
    from crdt_enc_tpu.models import canonical_bytes
    from crdt_enc_tpu.models.orset import ORSet, op_from_obj
    from crdt_enc_tpu.read.stable import StalenessError
    from crdt_enc_tpu.sim.linearize import oracle_fold

    opts = _daemon_opts_fn()

    async def scenario():
        remote = MemoryRemote()
        producers = [
            await Core.open(opts(MemoryStorage(remote))) for _ in range(R)
        ]
        reader = await Core.open(opts(MemoryStorage(remote)))
        oplog: dict = {}  # (actor, version) -> [op_obj, ...] plaintext
        total_ops = 0
        strong_s: list = []
        eventual_s: list = []
        lag_samples: list = []
        refusals = 0
        t0 = time.perf_counter()
        for rnd in range(ROUNDS):
            for pi, p in enumerate(producers):
                for w in range(WAVE):
                    member = f"m{pi}-{rnd}-{w}".encode()
                    ops = await p.update(
                        lambda s, a=p.actor_id, m=member: s.add_ctx(a, m)
                    )
                    oplog[(p.actor_id, p._local_meta.last_op_version)] = [
                        op.to_obj() for op in ops
                    ]
                    total_ops += 1
                if (rnd + pi) % PUB_EVERY == 0:
                    await p.compact()  # publish the cursor
            for _ in range(READS):
                te = time.perf_counter()
                await reader.read_remote()
                await reader.read()
                eventual_s.append(time.perf_counter() - te)
                ts = time.perf_counter()
                res = await reader.read(linearizable=True)
                strong_s.append(time.perf_counter() - ts)
                lag_samples.append(res.view.lag)
                # refusal-rate probe, untimed: a zero-staleness demand
                # refuses whenever the frontier trails the union — the
                # fraction of the run a max_lag=0 caller would have
                # been refused under this churn
                try:
                    await reader.read(
                        linearizable=True, max_lag=0, refresh=False
                    )
                except StalenessError:
                    refusals += 1
        # drain to full stability: every producer publishes its final
        # cursor and the reader observes EACH publication before the
        # next compact garbage-collects the snapshot that carries it —
        # cursor knowledge lives in snapshots, so a reader that never
        # sees one never counts that replica as caught up (the honest
        # wedge docs/strong_reads.md describes)
        for p in producers:
            await p.compact()
            await reader.read_remote()
        res = await reader.read(linearizable=True)
        wall = time.perf_counter() - t0
        lag_samples.append(res.view.lag)
        oracle, missing = oracle_fold(oplog, res.cursor)
        identical = (
            not missing
            and canonical_bytes(ORSet.from_obj(res.obj))
            == canonical_bytes(oracle)
        )
        covered = sum(res.cursor.counters.values())
        return (
            wall, total_ops, covered, strong_s, eventual_s, lag_samples,
            refusals, identical,
        )

    (wall, total_ops, covered, strong_s, eventual_s, lag_samples,
     refusals, identical) = asyncio.run(scenario())

    q = _nearest_rank

    result = {
        "metric": "strong_read_e2e_reads_per_sec",
        "config": f"strongread_{R}p",
        "value": round(len(strong_s) / sum(strong_s), 1),
        "unit": "reads/s",
        "reads_strong": len(strong_s),
        "reads_eventual": len(eventual_s),
        "refusals": refusals,
        "strong_ms": _quantiles_ms(strong_s),
        "eventual_ms": _quantiles_ms(eventual_s),
        "watermark_lag_versions": {
            "p50": q(lag_samples, 0.50),
            "p99": q(lag_samples, 0.99),
            "max": max(lag_samples),
        },
        "total_ops": total_ops,
        "final_covered_versions": covered,
        "wall_s": round(wall, 3),
        "byte_identical": identical,
        "backend": "cpu",
    }
    log(
        f"strong-read: {len(strong_s)} strong reads "
        f"(p99 {result['strong_ms'].get('p99_ms')}ms) vs eventual p99 "
        f"{result['eventual_ms'].get('p99_ms')}ms; watermark lag p99 "
        f"{result['watermark_lag_versions']['p99']} versions; "
        f"byte_identical={identical}"
    )
    print(json.dumps(result))
    if not identical:
        log(
            "FAILED: final strong read diverges from the oracle fold "
            "of its own cut — refusing to record"
        )
        raise SystemExit(1)
    if os.environ.get("BENCH_LOCAL_DISABLE") == "1":
        return
    _append_local({
        **result,
        "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"),
        "host_cpus": os.cpu_count(),
        "shape": {"producers": R, "rounds": ROUNDS, "wave": WAVE,
                  "reads_per_round": READS, "pub_every": PUB_EVERY},
    })


def bench_sim(smoke: bool):
    """Adversarial-simulator throughput (docs/simulation.md): schedules
    per second over seeded all-fault runs — the explorable-schedule
    depth per CI minute, tracked like any other perf surface.  The run
    refuses to record if ANY schedule violates an invariant (a broken
    protocol has no meaningful throughput).  Protocol-level simulation
    is CPU-bound by design, so records land in BENCH_LOCAL.jsonl
    without the TPU gate.

    Flags/envs: ``--replicas N`` (8), ``--steps M`` (250), ``--faults
    all|none|cls,cls`` (all), ``--population P`` (run P schedules
    concurrently through one shared substrate, sim/population.py — the
    record's config gains a ``_pP`` suffix so the serial baseline stays
    a separate trend series), BENCH_SIM_SEEDS (4 serial; 2·P
    population).

    Population refusal guard: after the clock stops, every schedule is
    re-run SERIALLY and its fingerprint compared — any divergence
    refuses the record (a population throughput that changed the
    results measured nothing)."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import logging

    logging.disable(logging.WARNING)  # quarantine warns are the point
    from crdt_enc_tpu.sim import generate, run_schedule
    from crdt_enc_tpu.tools.sim import _build_faults

    replicas = _flag_int("--replicas", 4 if smoke else 8)
    steps = _flag_int("--steps", 50 if smoke else 250)
    population = _flag_int("--population", 0)
    spec = "all"
    if "--faults" in sys.argv:
        i = sys.argv.index("--faults")
        if i + 1 >= len(sys.argv):
            raise SystemExit("--faults wants all|none|class,class")
        spec = sys.argv[i + 1]
    faults = _build_faults(spec)
    n_seeds = int(os.environ.get(
        "BENCH_SIM_SEEDS",
        (2 if smoke else 4) if population < 2 else 2 * population,
    ))

    from collections import Counter

    totals: Counter = Counter()
    total_steps = total_checks = quarantined = 0
    report = None
    t0 = time.perf_counter()
    if population > 1:
        from crdt_enc_tpu.sim import run_population

        schedules = [
            generate(seed, replicas, steps, faults)
            for seed in range(n_seeds)
        ]
        report = run_population(schedules, population=population)
        results = list(zip(schedules, report.results))
    else:
        results = []
        for seed in range(n_seeds):
            schedule = generate(seed, replicas, steps, faults)
            results.append((schedule, run_schedule(schedule)))
    wall = time.perf_counter() - t0
    for schedule, result in results:
        if not result.ok:
            raise SystemExit(
                f"sim seed {schedule.seed} violated an invariant: "
                f"{result.violation}"
                " — fix the bug (and commit the shrunk fixture); a broken"
                " protocol has no throughput to record"
            )
        totals.update(result.fault_stats)
        total_steps += result.steps_run
        total_checks += result.checks_run
        quarantined += result.quarantined
    if report is not None:
        # the serial-equivalence refusal guard (untimed: the record is
        # the population wall, the guard is the evidence behind it)
        from crdt_enc_tpu.sim import verify_serial_equality

        problems = verify_serial_equality(report)
        if problems:
            raise SystemExit(
                "population run diverged from its serial twins — "
                "refusing to record:\n  " + "\n  ".join(problems)
            )
    suffix = f"_p{population}" if population > 1 else ""
    result_rec = {
        "metric": "sim_schedules_per_sec",
        "config": f"sim_{replicas}r_{steps}s_{spec}{suffix}",
        "value": round(n_seeds / wall, 3),
        "unit": "schedules/s",
        "steps_per_sec": round(total_steps / wall, 1),
        "schedules": n_seeds,
        "replicas": replicas,
        "steps": steps,
        "faults": spec,
        "faults_survived": dict(sorted(totals.items())),
        "faults_survived_total": sum(totals.values()),
        "ingest_quarantined": quarantined,
        "quiescence_checks": total_checks,
        "violations": 0,
        "wall_s": round(wall, 3),
        "backend": "cpu",
    }
    if population > 1:
        result_rec["population"] = population
        result_rec["serial_equivalent"] = True
    log(
        f"sim: {n_seeds} schedules ({replicas} replicas x {steps} steps, "
        f"faults={spec}"
        + (f", population={population}" if population > 1 else "")
        + f") in {wall:.2f}s = {result_rec['value']} sched/s, "
        f"{result_rec['faults_survived_total']} faults survived"
    )
    print(json.dumps(result_rec))
    if os.environ.get("BENCH_LOCAL_DISABLE") == "1":
        return
    _append_local({
        **result_rec,
        "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"),
        "host_cpus": os.cpu_count(),
    })


def main():
    smoke = "--smoke" in sys.argv
    if "--sim" in sys.argv:
        bench_sim(smoke)
        return
    if "--e2e-strong-read" in sys.argv:
        e2e_strong_read(smoke)
        return
    if "--e2e-delta" in sys.argv:
        e2e_delta(smoke)
        return
    if "--e2e-streaming" in sys.argv:
        e2e_streaming(smoke)
        return
    if "--device-decode" in sys.argv:
        device_decode_exp(smoke)
        return
    if "--e2e-warm-open" in sys.argv:
        e2e_warm_open(smoke)
        return
    if "--e2e-multitenant" in sys.argv:
        e2e_multitenant(smoke)
        return
    if "--e2e-daemon" in sys.argv:
        e2e_daemon(smoke)
        return
    if "--e2e-idle-cycle" in sys.argv:
        e2e_idle_cycle(smoke)
        return
    N = int(os.environ.get("BENCH_OPS", 50_000 if smoke else 1_000_000))
    R = int(os.environ.get("BENCH_REPLICAS", 500 if smoke else 10_000))
    E = int(os.environ.get("BENCH_MEMBERS", 256 if smoke else 4096))
    N_HOST = min(N, int(os.environ.get("BENCH_HOST_OPS", 20_000 if smoke else 100_000)))
    ITERS = int(os.environ.get("BENCH_ITERS", 3))

    # Expect a TPU unless the caller pinned a host-first platform list or
    # is smoke-testing (a smoke run on a TPU-less box should fall through
    # to CPU, not stall through 4 probe timeouts).
    platforms = os.environ.get("JAX_PLATFORMS", "").lower()
    first_platform = platforms.split(",")[0].strip() if platforms else ""
    want_tpu = first_platform not in ("cpu",) and not smoke
    jax, dev = acquire_jax(want_tpu)

    import crdt_enc_tpu
    from crdt_enc_tpu import ops as K

    # compiles are excluded from the marginal timing, but the persistent
    # cache cuts the bench's own wall-clock on repeat runs
    crdt_enc_tpu.enable_compilation_cache()
    log(f"device: {dev.platform} ({dev.device_kind}); N={N} R={R} E={E}")

    kind, member, actor, counter = gen_columns(N, R, E)
    small = bool(counter.max() < 2 ** 15)
    variant_kws = {
        "fused": dict(impl="fused"),
        "two_pass": dict(impl="two_pass"),
    }
    if small:
        variant_kws["fused_i16"] = dict(impl="fused", small_counters=True)

    # the Pallas sorted one-hot-matmul fold (ops/pallas_fold.py): the
    # scatter phase rides the MXU instead of XLA's serialized scatter
    from crdt_enc_tpu.ops.pallas_fold import (
        MAX_COUNTER, MAX_ROWS, fold_cap, fused_defaults,
        orset_fold_pallas, orset_fold_pallas_fused, orset_pad_state,
        orset_retire, orset_unpad_state,
    )

    interpret = jax.default_backend() != "tpu"
    if counter.max() < MAX_COUNTER and N <= MAX_ROWS:
        tile_cap = fold_cap(member, E)

        def pallas_variant(layout):
            return dict(
                _fold=lambda c, a, r, kind, member, actor, counter:
                orset_fold_pallas(
                    c, a, r, kind, member, actor, counter,
                    num_members=E, num_replicas=R, tile_cap=tile_cap,
                    interpret=interpret, layout=layout,
                ),
            )

        # the MXU-native actor-blocked layout; the wide round-3 layout
        # stays as an on-hardware A/B (interpret mode is too slow to
        # time it twice on CPU)
        variant_kws["pallas_bf16"] = pallas_variant("ablk")
        if not interpret:
            variant_kws["pallas_wide"] = pallas_variant("wide")

        # round-5 flagship: normalize tail fused into the kernel
        # epilogue, deferred rm retirement, host-routed hi-limb skip
        fd = fused_defaults(E, R, int(counter.max()))

        def fused_single(c, a, r, kind, member, actor, counter):
            cp, ap, rp = orset_pad_state(
                c, a, r, num_members=E, num_replicas=R, h_blk=fd["h_blk"])
            out = orset_fold_pallas_fused(
                cp, ap, rp, kind, member, actor, counter,
                num_members=E, num_replicas=R, tile_cap=tile_cap,
                interpret=interpret, **fd)
            return orset_unpad_state(*out, num_members=E, num_replicas=R)

        def fused_chained(n_folds):
            import jax.numpy as jnp

            @jax.jit
            def run(c, a, r, kind, member, actor, counter):
                cp, ap, rp = orset_pad_state(
                    c, a, r, num_members=E, num_replicas=R,
                    h_blk=fd["h_blk"])

                def body(carry, _):
                    shift = (carry[0][0] + carry[1][0, 0]) % jnp.int32(
                        kind.shape[0])
                    rolled = [
                        jnp.roll(x, shift)
                        for x in (kind, member, actor, counter)
                    ]
                    # fixed initial planes + carry-derived roll (the
                    # protocol of `chained` below); deferred retirement
                    # inside the chain, one finalize after — byte-equal
                    # to the eager chain (ops/pallas_fold.py proof)
                    out = orset_fold_pallas_fused(
                        cp, ap, rp, *rolled,
                        num_members=E, num_replicas=R, tile_cap=tile_cap,
                        interpret=interpret, retire_rm=False, **fd)
                    return out, ()
                carry, _ = jax.lax.scan(
                    body, (cp, ap, rp), None, length=n_folds)
                ck, ad, rmv = carry
                return orset_unpad_state(
                    ck, ad, orset_retire(ck, rmv),
                    num_members=E, num_replicas=R)
            return run

        variant_kws["pallas_fused"] = dict(
            _fold=fused_single, _chained=fused_chained)

    def fold_call(kw):
        """A (carry, rows...) -> carry fold closure for one variant."""
        fold = kw.get("_fold")
        if fold is not None:
            return fold
        kw = {k: v for k, v in kw.items() if not k.startswith("_")}
        return lambda c, a, r, kind, member, actor, counter: K.orset_fold(
            c, a, r, kind, member, actor, counter,
            num_members=E, num_replicas=R, **kw,
        )

    # ---- correctness spot-check: host vs TPU byte equality on a subsample,
    # for EVERY variant that competes below (the published number must come
    # from a checked code path)
    n_chk = min(N, 20_000)
    h_state, _ = host_fold(kind[:n_chk], member[:n_chk], actor[:n_chk], counter[:n_chk], R)
    from crdt_enc_tpu.ops.columnar import Vocab, orset_planes_to_state
    from crdt_enc_tpu.utils import codec

    mem_v = Vocab(range(E))
    rep_v = Vocab(range(R))
    c0 = np.zeros(R, np.int32)
    a0 = np.zeros((E, R), np.int32)
    r0 = np.zeros((E, R), np.int32)
    h_bytes = codec.pack(h_state.to_obj())
    diverged = []
    for name, kw in variant_kws.items():
        try:
            ck, ad, rmv = fold_call(kw)(
                c0, a0, r0, kind[:n_chk], member[:n_chk], actor[:n_chk],
                counter[:n_chk],
            )
        except Exception as e:  # e.g. a dot dtype Mosaic can't lower
            log(f"WARNING: variant {name} failed to compile/run ({e!r}); excluded")
            diverged.append(name)
            continue
        t_state = orset_planes_to_state(
            np.asarray(ck), np.asarray(ad), np.asarray(rmv), mem_v, rep_v
        )
        ok = codec.pack(t_state.to_obj()) == h_bytes
        log(f"byte-equality[{name}] (n={n_chk}): {'OK' if ok else 'MISMATCH'}")
        if not ok:
            log(f"WARNING: variant {name} diverged from host reference; excluded")
            diverged.append(name)
    for name in diverged:
        del variant_kws[name]
    if not variant_kws:
        raise SystemExit("every fold variant diverged from the host reference")

    # ---- full-batch byte equality: the PUBLISHED shape (all N rows), not
    # just the 20k prefix — tile skew, the sliding windows, and the
    # hi-limb skip only engage at scale.  Host truth at N=1M is the
    # vectorized sparse host fold, itself tied to the per-op host
    # reference on the subsample right here; the first variant is checked
    # byte-for-byte through planes→state→pack, the rest plane-equal on
    # device against it (equality is transitive, and one 300MB+ plane
    # pull over the tunnel is enough).
    full_checked = False
    if os.environ.get("BENCH_FULL_CHECK", "1") == "1":
        import jax.numpy as jnp

        from crdt_enc_tpu.models import ORSet as HostORSet
        from crdt_enc_tpu.ops.columnar import orset_fold_sparse_host

        sub_sparse = orset_fold_sparse_host(
            HostORSet(), kind[:n_chk], member[:n_chk], actor[:n_chk],
            counter[:n_chk], mem_v, rep_v,
        )
        if codec.pack(sub_sparse.to_obj()) != h_bytes:
            raise SystemExit(
                "sparse host fold diverged from the per-op host reference "
                "on the subsample — full-batch truth source is broken"
            )
        t0 = time.perf_counter()
        full_host = orset_fold_sparse_host(
            HostORSet(), kind, member, actor, counter, mem_v, rep_v
        )
        full_bytes = codec.pack(full_host.to_obj())
        log(f"full-batch host fold (N={N}): {time.perf_counter() - t0:.2f}s")
        full_args = [
            jax.device_put(x, dev)
            for x in (c0, a0, r0, kind, member, actor, counter)
        ]
        ref_planes = None
        for name, kw in list(variant_kws.items()):
            out = fold_call(kw)(*full_args)
            jax.block_until_ready(out)
            if ref_planes is None:
                ck, ad, rmv = (np.asarray(x) for x in out)
                st = orset_planes_to_state(ck, ad, rmv, mem_v, rep_v)
                ok = codec.pack(st.to_obj()) == full_bytes
                if ok:
                    ref_planes = out
            else:
                ok = all(
                    bool(jnp.array_equal(x, y))
                    for x, y in zip(out, ref_planes)
                )
            log(
                f"full-batch byte-equality[{name}] (N={N}): "
                f"{'OK' if ok else 'MISMATCH'}"
            )
            if not ok:
                log(f"WARNING: variant {name} diverged at the full batch; "
                    "excluded")
                del variant_kws[name]
        if not variant_kws:
            raise SystemExit("every variant diverged at the full batch")
        del full_args, ref_planes
        full_checked = True

    # ---- single-core host baseline (capped subsample; O(n) per-op loop)
    # under the pinned median-of-N protocol (see host_median above)
    def host_once():
        state, t = host_fold(
            kind[:N_HOST], member[:N_HOST], actor[:N_HOST], counter[:N_HOST], R
        )
        return t, state

    t_host, host_times, _ = host_median(host_once)
    host_rate = N_HOST / t_host
    stats = host_stats(host_times)
    log(
        f"host: {N_HOST} ops, median of {len(host_times)}: {t_host:.3f}s → "
        f"{host_rate:,.0f} ops/s (samples {stats['host_samples_s']}, "
        f"spread {stats['host_spread_pct']:.0f}%)"
    )

    # ---- TPU fold: full batch, compile excluded.  Per-fold device time is
    # the marginal cost inside a K-chained scan (see module docstring) —
    # the chain carry makes every fold data-dependent on the last.
    # Tiny smoke shapes fold in ~µs — chain enough folds that the marginal
    # signal clears the ~±20ms tunnel-latency jitter.
    CHAIN = int(os.environ.get("BENCH_CHAIN", 1000 if smoke else 20))
    args = [jax.device_put(x, dev) for x in (c0, a0, r0, kind, member, actor, counter)]

    def chained(n_folds, **kw):
        """Marginal-measurement chain.  Anchoring: each iteration feeds
        the FIXED initial planes and a carry-derived roll of the op rows
        (legal — the fold is order-independent, so every iteration
        computes the same planes), rather than chaining the fold onto its
        own output.  The roll makes every iteration data-dependent on the
        last (XLA cannot hoist or elide any), and the fixed initial clock
        keeps the replay gate OPEN every iteration — a fold chained to
        its own fixpoint sees every add stale, which under-measures any
        variant with value-dependent work (e.g. the Pallas kernel's
        hi-limb skip)."""
        if "_chained" in kw:  # variant with its own carry layout
            return kw["_chained"](n_folds)
        fold = fold_call(kw)

        @jax.jit
        def run(c, a, r, kind, member, actor, counter):
            import jax.numpy as jnp

            def body(carry, _):
                shift = (carry[0][0] + carry[1][0, 0]) % jnp.int32(
                    kind.shape[0]
                )
                rolled = [
                    jnp.roll(x, shift)
                    for x in (kind, member, actor, counter)
                ]
                return fold(c, a, r, *rolled), ()
            carry, _ = jax.lax.scan(body, (c, a, r), None, length=n_folds)
            return carry
        return run

    def timed(fn):
        out = fn(*args)
        jax.block_until_ready(out)  # compile + warmup
        times = []
        for _ in range(ITERS):
            t0 = time.perf_counter()
            out = fn(*args)
            jax.block_until_ready(out)
            force_completion(out)
            times.append(time.perf_counter() - t0)
        return min(times)

    # Below this marginal the measurement is tunnel jitter, not device time
    # (jitter spread over CHAIN folds).  A variant whose marginal lands
    # under the floor is NOISE — it must not win "best" and its rate must
    # not be published; raise BENCH_CHAIN until the signal clears the floor.
    NOISE_FLOOR = TUNNEL_JITTER_S / CHAIN
    # Round-robin timing (round 5): single-position measurements swing
    # ±2-3ms with device/tunnel weather, so sequential per-variant
    # timing hands the last-measured variant the weather lottery.
    # Compile everything first, then interleave BENCH_ROUNDS passes
    # across variants and keep per-variant minima — variants compete
    # under the same weather.
    ROUNDS = int(os.environ.get("BENCH_ROUNDS", 2))
    fns = {}
    for name, kw in variant_kws.items():
        fns[name] = (chained(1, **kw), chained(1 + CHAIN, **kw))
        for f in fns[name]:
            import jax as _jax

            _jax.block_until_ready(f(*args))  # compile now
        log(f"compiled {name}")
    # Every pass's marginal is RECORDED per variant (the same
    # transparency the host samples get): the published number is the
    # min of the above-floor samples, and auditors can see the whole
    # distribution — including discarded sub-floor glitches — in the
    # evidence file, so a single-sample minimum can be judged against
    # its siblings.
    samples, single_dispatch = {n: [] for n in variant_kws}, {}
    for rd in range(ROUNDS):
        for name in variant_kws:
            f1, fk = fns[name]
            t1 = timed(f1)
            tk = timed(fk)
            t_marginal = (tk - t1) / CHAIN
            single_dispatch[name] = min(
                single_dispatch.get(name, t1), t1
            )
            samples[name].append(t_marginal)
            flag = (
                "" if t_marginal > NOISE_FLOOR
                else "  [sub-floor: noise, not device time]"
            )
            log(f"  round {rd} {name}: {t_marginal * 1e3:.2f} ms{flag}")
    variants, sub_floor_discards = {}, {}
    for name, ts in samples.items():
        valid = [t for t in ts if t > NOISE_FLOOR]
        sub_floor_discards[name] = len(ts) - len(valid)
        if not valid:
            log(
                f"tpu[{name}]: every pass below the "
                f"{NOISE_FLOOR * 1e3:.2f}ms noise floor — excluded"
            )
            continue
        variants[name] = min(valid)
        log(
            f"tpu[{name}]: single-dispatch {single_dispatch[name]:.4f}s "
            f"(incl. ~0.1s tunnel round-trip); best marginal "
            f"{variants[name] * 1e3:.2f}ms/fold → "
            f"{N / variants[name]:,.0f} ops/s"
            + (f"  [{sub_floor_discards[name]} sub-floor discarded]"
               if sub_floor_discards[name] else "")
        )
    method = "marginal_chain"
    if not variants:
        log(
            f"WARNING: every variant fell below the {NOISE_FLOOR * 1e3:.2f}ms "
            f"noise floor; rerun with a larger BENCH_CHAIN (current {CHAIN}). "
            "Falling back to single-dispatch wall-clock (tunnel latency "
            "INCLUDED) — a strict over-estimate of device time."
        )
        variants = single_dispatch
        method = "single_dispatch_upper_bound"
    # Roofline gate: any variant whose marginal implies more than HBM
    # peak on the fold's minimum traffic (read+write both planes + the
    # op columns + the clock) is a measurement artifact, not a kernel —
    # drop it loudly instead of publishing an impossible number.
    on_tpu = jax.default_backend() == "tpu"
    bytes_model = orset_fold_bytes_model(N, E, R)
    for name in list(variants):
        pct = roofline_pct(bytes_model, variants[name], on_tpu)
        if pct is not None and pct > 100.0:
            log(
                f"WARNING: variant {name} implies {pct:.0f}% of HBM peak "
                f"({variants[name]*1e3:.2f}ms for ≥{bytes_model/1e6:.0f}MB) "
                "— impossible; chain was hoisted/elided. Excluded."
            )
            del variants[name]
    if not variants:
        raise SystemExit("every variant failed the roofline sanity gate")
    best = min(variants, key=variants.get)
    t_tpu = variants[best]
    tpu_rate = N / t_tpu
    log(f"best variant: {best}")
    pct_hbm = roofline_pct(bytes_model, t_tpu, on_tpu)
    log(f"roofline: ≥{bytes_model/1e6:.0f}MB/fold → {pct_hbm}% of HBM peak")

    # same key + workload as suite config 3 — one pin serves both
    ratio_fields = pinned_ratio_fields(
        "orset_10kx1M", {"N": N, "R": R, "E": E, "n_host": N_HOST},
        tpu_rate, tpu_rate / host_rate,
    )
    ratio_fields.pop("_ratio_raw", None)  # aggregation-only field
    result = {
        "metric": "orset_compaction_fold_ops_per_sec",
        "value": round(tpu_rate, 1),
        "unit": "ops/s",
        **ratio_fields,
        # which timing method produced `value` — consumers must not compare
        # a latency-bound fallback number against a marginal-chain number
        "method": method,
        "best_variant": best,
        # bytes any implementation of this fold must touch, and the % of
        # v5e HBM peak the measured marginal implies on that model —
        # regressions and headroom visible mechanically (>100% = rejected)
        "bytes_model": bytes_model,
        "pct_hbm_peak": pct_hbm,
        # byte equality was checked at the full published shape, not just
        # the subsample (VERDICT r3 item 4)
        "full_batch_equal": full_checked,
        "backend": dev.platform,
    }
    print(json.dumps(result))
    # persist the run (full per-variant table) so a later capture-time
    # tunnel outage cannot erase this round's verified numbers.  Only
    # real-TPU runs go into the committed evidence file — CPU smoke runs
    # would pollute it (override with BENCH_LOCAL_ALL=1 for testing).
    if os.environ.get("BENCH_LOCAL_DISABLE") == "1":  # e.g. harness tests
        return
    if dev.platform != "tpu" and os.environ.get("BENCH_LOCAL_ALL") != "1":
        return
    _append_local({
        **result,
        "ts": datetime.datetime.now(datetime.timezone.utc).isoformat(
            timespec="seconds"),
        "device_kind": dev.device_kind,
        "shape": {"N": N, "R": R, "E": E, "chain": CHAIN, "iters": ITERS},
        "host_rate": round(host_rate, 1),
        **stats,
        "marginals_ms": {
            k: round(v * 1e3, 3) for k, v in variants.items()
        },
        # the full per-variant sample distributions (incl. sub-floor
        # glitches), so a published minimum can be audited against its
        # sibling passes — a lone fast outlier is visible as such
        "marginal_samples_ms": {
            k: [round(t * 1e3, 3) for t in ts]
            for k, ts in samples.items()
        },
        "single_dispatch_s": {
            k: round(v, 4) for k, v in single_dispatch.items()
        },
    })


if __name__ == "__main__":
    main()

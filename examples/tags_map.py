"""Encrypted shared tag index — a causal map of OR-Sets over a synced dir.

Shows the catalogue's composite type (``CrdtMap<orset>``,
models/crdtmap.py): each key holds a nested OR-Set of tags, a key
remove deletes exactly the observed history (concurrent tag adds
survive the remove — the same add-wins discipline as the flat set),
and compaction folds the whole log through the columnar map fold.
Replicas are devices sharing one ``remote`` directory synced by an
external tool, the reference's replication model (README.md:3-11).

    python examples/tags_map.py --data ./tags --local laptop tag inbox urgent
    python examples/tags_map.py --data ./tags --local phone  tag inbox later
    python examples/tags_map.py --data ./tags --local phone  list
    python examples/tags_map.py --data ./tags --local laptop untag inbox urgent
    python examples/tags_map.py --data ./tags --local laptop drop inbox
    python examples/tags_map.py --data ./tags --local laptop compact
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from crdt_enc_tpu.backends import FsStorage, PassphraseKeyCryptor, XChaChaCryptor
from crdt_enc_tpu.core import Core, OpenOptions, map_adapter
from crdt_enc_tpu.models.orset import AddOp
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


async def open_replica(data_dir: str, local: str, passphrase: str) -> Core:
    root = Path(data_dir)
    return await Core.open(
        OpenOptions(
            storage=FsStorage(str(root / local), str(root / "remote")),
            cryptor=XChaChaCryptor(),
            key_cryptor=PassphraseKeyCryptor(passphrase),
            adapter=map_adapter(b"orset"),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=True,
        )
    )


async def run(args) -> int:
    core = await open_replica(args.data, args.local, args.passphrase)
    await core.read_remote()  # converge with whatever other devices wrote

    if args.cmd == "tag":
        key, tag = args.key, args.tag
        await core.update(
            lambda s: s.update_ctx(
                core.actor_id, key, lambda child, dot: AddOp(tag, dot)
            )
        )
        print(f"tagged {key!r} with {tag!r}")
    elif args.cmd == "untag":
        key, tag = args.key, args.tag

        def build(s):
            child = s.get(key)
            if child is None or not child.contains(tag):
                return None  # nothing observed to remove
            return s.update_ctx(
                core.actor_id, key, lambda c, dot: c.rm_ctx(tag)
            )

        ops = await core.update(build)
        print(f"untagged {key!r}: {tag!r}" if ops else "nothing to untag")
    elif args.cmd == "drop":
        key = args.key

        def build(s):
            if not s.contains(key):
                return None
            return s.rm_ctx(key)

        ops = await core.update(build)
        print(f"dropped {key!r}" if ops else "no such key")
    elif args.cmd == "list":
        rows = core.with_state(
            lambda s: {
                k: sorted(str(t) for t in s.get(k).members())
                for k in s.keys()
            }
        )
        if not rows:
            print("(empty)")
        for k, tags in rows.items():
            print(f"{k}: {', '.join(tags) or '(no tags)'}")
    elif args.cmd == "compact":
        await core.compact()
        print(f"compacted; cursor {core.info().next_op_versions.to_obj()}")
    return 0


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--data", required=True)
    ap.add_argument("--local", required=True, help="this device's name")
    ap.add_argument("--passphrase", default="hunter2")
    sub = ap.add_subparsers(dest="cmd", required=True)
    p = sub.add_parser("tag")
    p.add_argument("key")
    p.add_argument("tag")
    p = sub.add_parser("untag")
    p.add_argument("key")
    p.add_argument("tag")
    p = sub.add_parser("drop")
    p.add_argument("key")
    sub.add_parser("list")
    sub.add_parser("compact")
    return asyncio.run(run(ap.parse_args()))


if __name__ == "__main__":
    raise SystemExit(main())

"""Encrypted shared todo list — an OR-Set over a synced directory.

A fuller tour than counter_sync.py: observed-remove set semantics (add wins
over a concurrent remove of an *older* observation), key rotation without
re-encryption (``rotate_key``), and compaction folding the op log into one
sealed snapshot.  Every replica is a device pointing at the same ``remote``
directory (in production, synced by an external tool — the replication
model of the reference, README.md:3-11).

    python examples/todo_orset.py --data ./todo --local laptop add "buy milk"
    python examples/todo_orset.py --data ./todo --local phone  list
    python examples/todo_orset.py --data ./todo --local phone  done "buy milk"
    python examples/todo_orset.py --data ./todo --local laptop rotate-key
    python examples/todo_orset.py --data ./todo --local laptop compact
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from crdt_enc_tpu.backends import FsStorage, PassphraseKeyCryptor, XChaChaCryptor
from crdt_enc_tpu.core import Core, OpenOptions, orset_adapter
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


async def open_replica(data_dir: str, local: str, passphrase: str) -> Core:
    root = Path(data_dir)
    core = await Core.open(
        OpenOptions(
            storage=FsStorage(str(root / local), str(root / "remote")),
            cryptor=XChaChaCryptor(),
            key_cryptor=PassphraseKeyCryptor(passphrase),
            adapter=orset_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=True,
        )
    )
    await core.read_remote()
    return core


async def run(args) -> None:
    core = await open_replica(args.data, args.local, args.passphrase)
    if args.cmd == "add":
        item = args.item.encode()
        await core.update(lambda s: s.add_ctx(core.actor_id, item))
        print(f"[{args.local}] added {args.item!r}")
    elif args.cmd == "done":
        item = args.item.encode()
        # rm_ctx removes the observed add-dots; an add this replica has
        # not yet seen survives (observed-remove semantics)
        op = core.with_state(lambda s: s.rm_ctx(item))
        if op.ctx.is_empty():
            print(f"[{args.local}] {args.item!r} not in the list here")
        else:
            await core.apply_ops([op])
            print(f"[{args.local}] done {args.item!r}")
    elif args.cmd == "list":
        items = core.with_state(lambda s: s.members())
        print(f"[{args.local}] {len(items)} open item(s):")
        for m in items:
            print(f"  - {m.decode(errors='replace')}")
    elif args.cmd == "rotate-key":
        key = await core.rotate_key()
        print(
            f"[{args.local}] rotated data key; new writes seal with "
            f"{key.id.hex()[:8]}…, old files stay readable"
        )
    elif args.cmd == "compact":
        await core.compact()
        print(
            f"[{args.local}] compacted: op log folded into one sealed snapshot"
        )


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--data", default="./todo")
    ap.add_argument("--local", default="dev-a")
    ap.add_argument("--passphrase", default="example-passphrase")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("list")
    p = sub.add_parser("add")
    p.add_argument("item")
    p = sub.add_parser("done")
    p.add_argument("item")
    sub.add_parser("rotate-key")
    sub.add_parser("compact")
    asyncio.run(run(ap.parse_args()))


if __name__ == "__main__":
    main()

"""End-to-end example — the rebuild of the reference's example app
(examples/test/src/main.rs:12-57): assemble the real backends (filesystem
storage + XChaCha20-Poly1305 cryptor + passphrase key cryptor), open a
replica holding an ``MVReg`` of integers, ingest whatever other replicas
left in the shared remote, then write ``max(values) + 1``.

Unlike the reference's example this one also exercises ``compact`` (there it
is commented out, main.rs:41 — its compaction path had a write/read format
asymmetry, SURVEY.md §3.4; ours round-trips).

Run it twice with the same --data dir and watch the value climb; point two
different --local names at one shared remote to emulate two synced devices:

    python examples/counter_sync.py --data ./data --local dev-a
    python examples/counter_sync.py --data ./data --local dev-b
"""

from __future__ import annotations

import argparse
import asyncio
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from crdt_enc_tpu.backends import FsStorage, PassphraseKeyCryptor, XChaChaCryptor
from crdt_enc_tpu.core import Core, OpenOptions, mvreg_adapter
from crdt_enc_tpu.utils.versions import DEFAULT_DATA_VERSION_1


async def run(data_dir: str, local_name: str, passphrase: str, compact: bool) -> int:
    root = Path(data_dir)
    core = await Core.open(
        OpenOptions(
            storage=FsStorage(str(root / local_name), str(root / "remote")),
            cryptor=XChaChaCryptor(),
            key_cryptor=PassphraseKeyCryptor(passphrase),
            adapter=mvreg_adapter(),
            supported_data_versions=(DEFAULT_DATA_VERSION_1,),
            current_data_version=DEFAULT_DATA_VERSION_1,
            create=True,
        )
    )
    await core.read_remote()

    seen = core.with_state(lambda s: s.read().values)
    value = max((int(v) for v in seen), default=0) + 1
    print(f"[{local_name}] saw {sorted(int(v) for v in seen)} -> writing {value}")

    # derive the write op under the core's writer lock, then persist it
    await core.update(lambda s: s.write_ctx(core.actor_id, value))

    if compact:
        await core.compact()
        print(f"[{local_name}] compacted: op tail folded into one snapshot")
    return value


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--data", default="./data", help="root holding local dirs + shared remote")
    ap.add_argument("--local", default="dev-a", help="this replica's local dir name")
    ap.add_argument("--passphrase", default="example-passphrase")
    ap.add_argument("--compact", action="store_true", help="compact after writing")
    args = ap.parse_args()
    asyncio.run(run(args.data, args.local, args.passphrase, args.compact))


if __name__ == "__main__":
    main()
